"""paddle.fft — spectral ops over jnp.fft (XLA FFT on TPU).

Reference: python/paddle/fft.py backed by the fft_c2c/fft_r2c/fft_c2r
yaml ops (/root/reference/paddle/phi/api/yaml/ops.yaml) with cuFFT/oneMKL
kernels; XLA lowers the same decompositions natively.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply_op, wrap

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]


def _op1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_=None, name__=None,
           **kw):
        return apply_op(name, lambda a: fn(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = name
    return op


def _op2(name, fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_=None, **kw):
        return apply_op(name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = name
    return op


def _opn(name, fn):
    def op(x, s=None, axes=None, norm="backward", name_=None, **kw):
        return apply_op(name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = name
    return op


fft = _op1("fft", jnp.fft.fft)
ifft = _op1("ifft", jnp.fft.ifft)
rfft = _op1("rfft", jnp.fft.rfft)
irfft = _op1("irfft", jnp.fft.irfft)
hfft = _op1("hfft", jnp.fft.hfft)
ihfft = _op1("ihfft", jnp.fft.ihfft)
fft2 = _op2("fft2", jnp.fft.fft2)
ifft2 = _op2("ifft2", jnp.fft.ifft2)
rfft2 = _op2("rfft2", jnp.fft.rfft2)
irfft2 = _op2("irfft2", jnp.fft.irfft2)
fftn = _opn("fftn", jnp.fft.fftn)
ifftn = _opn("ifftn", jnp.fft.ifftn)
rfftn = _opn("rfftn", jnp.fft.rfftn)
irfftn = _opn("irfftn", jnp.fft.irfftn)


def _hfftn_impl(a, s, axes, norm):
    # jnp has no hfftn: hermitian transform along the LAST axis composed
    # with a complex fftn over the preceding axes (scipy.fft.hfftn
    # semantics; per-stage norm composes to the overall scaling)
    if axes is None:
        axes = tuple(range(a.ndim)) if s is None else \
            tuple(range(a.ndim - len(s), a.ndim))
    axes = tuple(axes)
    s_list = list(s) if s is not None else None
    pre_axes, last = axes[:-1], axes[-1]
    if pre_axes:
        pre_s = s_list[:-1] if s_list else None
        a = jnp.fft.fftn(a, s=pre_s, axes=pre_axes, norm=norm)
    n_last = s_list[-1] if s_list else None
    return jnp.fft.hfft(a, n=n_last, axis=last, norm=norm)


def _ihfftn_impl(a, s, axes, norm):
    if axes is None:
        axes = tuple(range(a.ndim)) if s is None else \
            tuple(range(a.ndim - len(s), a.ndim))
    axes = tuple(axes)
    s_list = list(s) if s is not None else None
    pre_axes, last = axes[:-1], axes[-1]
    n_last = s_list[-1] if s_list else None
    a = jnp.fft.ihfft(a, n=n_last, axis=last, norm=norm)
    if pre_axes:
        pre_s = s_list[:-1] if s_list else None
        a = jnp.fft.ifftn(a, s=pre_s, axes=pre_axes, norm=norm)
    return a


hfft2 = _op2("hfft2", _hfftn_impl)
ihfft2 = _op2("ihfft2", _ihfftn_impl)
hfftn = _opn("hfftn", _hfftn_impl)
ihfftn = _opn("ihfftn", _ihfftn_impl)


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift",
                    lambda a: jnp.fft.ifftshift(a, axes=axes), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return wrap(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return wrap(jnp.fft.rfftfreq(n, d))
