"""Native (C++) runtime components, loaded via ctypes.

The reference implements its runtime layer natively (SURVEY §2.2: memory,
platform, distributed bootstrap, profiler, data feed are C++). Here the
XLA-facing compute path is jax; the host runtime pieces that survive XLA are
C++ in csrc/ and built on first use with the in-tree toolchain (g++ —
pybind11 is unavailable, so the ABI is plain C + ctypes):

- tcp_store.cc   — rendezvous KV store (reference tcp_store.cc)
- host_tracer.cc — profiler span recorder + chrome-trace export
- shm_ring.cc    — shared-memory DataLoader batch transport

`lib()` returns the loaded CDLL or None (callers must degrade gracefully to
their pure-Python fallbacks so the framework works without a compiler).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = ("tcp_store.cc", "host_tracer.cc", "shm_ring.cc")


def _src_digest(srcs) -> str:
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _build(src_dir: str, out_path: str) -> bool:
    srcs = [os.path.join(src_dir, s) for s in _SRC]
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", out_path] + srcs + ["-lrt"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        return proc.returncode == 0
    except Exception:
        return False


def lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        here = os.path.dirname(os.path.abspath(__file__))
        src_dir = os.path.join(here, "csrc")
        out = os.path.join(here, "libpaddle_tpu_native.so")
        srcs = [os.path.join(src_dir, s) for s in _SRC]
        # staleness is keyed on a content hash of the sources (mtimes are
        # not preserved by git checkout); the .so is never committed.
        stamp = out + ".sha256"
        try:
            digest = _src_digest(srcs)
        except OSError:
            return None  # sources missing: degrade to pure-Python fallbacks
        stale = not os.path.exists(out)
        if not stale:
            try:
                with open(stamp) as f:
                    stale = f.read().strip() != digest
            except OSError:
                stale = True
        if stale:
            if not _build(src_dir, out):
                return None
            with open(stamp, "w") as f:
                f.write(digest)
        try:
            cdll = ctypes.CDLL(out)
        except OSError:
            return None
        _configure(cdll)
        _lib = cdll
        return _lib


def build_capi() -> str | None:
    """Build libpaddle_inference_c.so — the C serving ABI (reference
    capi_exp PD_* surface) over the Python Predictor via an embedded
    CPython interpreter (csrc/pd_capi.cc). Returns the .so path or None.

    Separate from the main native lib because it links libpython; host
    apps dlopen it, include csrc/pd_inference_c.h, and must export
    PYTHONPATH so `import paddle_tpu` resolves inside the embedded
    interpreter."""
    import sysconfig

    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "csrc", "pd_capi.cc")
    header = os.path.join(here, "csrc", "pd_inference_c.h")
    out = os.path.join(here, "libpaddle_inference_c.so")
    stamp = out + ".sha256"
    try:
        digest = _src_digest([src, header])
    except OSError:
        return None
    if os.path.exists(out):
        try:
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return out
        except OSError:
            pass
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           f"-I{inc}", "-o", out, src, f"-L{libdir}",
           f"-Wl,-rpath,{libdir}", f"-lpython{pyver}"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=180)
        if proc.returncode != 0:
            return None
    except Exception:
        return None
    with open(stamp, "w") as f:
        f.write(digest)
    return out


def _configure(l):
    c = ctypes
    l.tcp_store_server_start.restype = c.c_void_p
    l.tcp_store_server_start.argtypes = [c.c_int]
    l.tcp_store_server_port.restype = c.c_int
    l.tcp_store_server_port.argtypes = [c.c_void_p]
    l.tcp_store_server_stop.argtypes = [c.c_void_p]
    l.tcp_store_client_connect.restype = c.c_void_p
    l.tcp_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    l.tcp_store_client_close.argtypes = [c.c_void_p]
    l.tcp_store_set.restype = c.c_int
    l.tcp_store_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]
    l.tcp_store_delete.restype = c.c_int
    l.tcp_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    l.tcp_store_get.restype = c.c_int
    l.tcp_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]
    l.tcp_store_add.restype = c.c_longlong
    l.tcp_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_longlong]
    l.tcp_store_wait.restype = c.c_int
    l.tcp_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_char_p,
                                 c.c_int]
    l.host_tracer_start.argtypes = []
    l.host_tracer_stop.restype = c.c_int
    l.host_tracer_stop.argtypes = [c.c_char_p]
    l.host_tracer_record.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64]
    l.host_tracer_now.restype = c.c_uint64
    l.host_tracer_enabled.restype = c.c_int
    l.host_tracer_event_count.restype = c.c_int
    l.shm_ring_open.restype = c.c_void_p
    l.shm_ring_open.argtypes = [c.c_char_p, c.c_int, c.c_uint64, c.c_uint64]
    l.shm_ring_push.restype = c.c_int
    l.shm_ring_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    l.shm_ring_pop.restype = c.c_longlong
    l.shm_ring_pop.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    l.shm_ring_size.restype = c.c_uint64
    l.shm_ring_size.argtypes = [c.c_void_p]
    l.shm_ring_close.argtypes = [c.c_void_p]
    l.shm_ring_free.argtypes = [c.c_void_p]
