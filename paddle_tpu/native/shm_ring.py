"""ShmRing — ctypes wrapper over the native POSIX shared-memory ring
(csrc/shm_ring.cc). Single-producer/single-consumer per ring; the
DataLoader gives each worker its own ring (reference analog:
fluid/memory/allocation/mmap_allocator.cc + imperative/data_loader.cc
shared-memory batch transport)."""
from __future__ import annotations

import ctypes

from . import lib


class ShmRing:
    def __init__(self, name: str, owner: bool, n_slots: int = 4,
                 slot_bytes: int = 8 << 20):
        l = lib()
        if l is None:
            raise RuntimeError("native library unavailable")
        self._l = l
        self._h = l.shm_ring_open(name.encode(), 1 if owner else 0,
                                  n_slots, slot_bytes)
        if not self._h:
            raise RuntimeError(f"shm_ring_open({name!r}) failed")
        self.name = name
        self.slot_bytes = slot_bytes

    @property
    def payload_capacity(self) -> int:
        return self.slot_bytes - 8

    def push(self, data: bytes) -> bool:
        """False if the payload exceeds the slot capacity (caller falls
        back to another transport); raises if the ring is closed."""
        rc = self._l.shm_ring_push(self._h, data, len(data))
        if rc == -2:
            return False
        if rc == -1:
            raise BrokenPipeError("shm ring closed")
        return True

    def pop(self, timeout_ms: int = -1) -> bytes:
        cap = self.slot_bytes
        buf = ctypes.create_string_buffer(cap)
        n = self._l.shm_ring_pop(self._h, buf, cap, timeout_ms)
        if n == -1:
            raise BrokenPipeError("shm ring closed")
        if n == -3:
            raise TimeoutError("shm ring pop timed out")
        if n < 0:
            raise RuntimeError(f"shm_ring_pop error {n}")
        return buf.raw[:n]

    def close(self):
        if self._h:
            self._l.shm_ring_close(self._h)

    def free(self):
        if self._h:
            self._l.shm_ring_free(self._h)
            self._h = None


def available() -> bool:
    return lib() is not None
