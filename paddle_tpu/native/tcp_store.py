"""TCPStore: Python wrapper over the native store, with a pure-Python
fallback (threading + sockets) so the API always works.

API mirrors the reference's paddle.distributed TCPStore usage
(/root/reference/paddle/phi/core/distributed/store/tcp_store.h): the rank-0
host runs the master; every rank gets set/get/add/wait.
"""
from __future__ import annotations

import ctypes
import threading
import time
from typing import Optional

from . import lib


class TCPStoreServer:
    def __init__(self, port: int = 0):
        l = lib()
        if l is not None:
            self._h = l.tcp_store_server_start(port)
            if not self._h:
                raise RuntimeError(f"TCPStore server failed to bind :{port}")
            self._l = l
            self.port = l.tcp_store_server_port(self._h)
            self._py = None
        else:  # pure-python fallback
            self._l = None
            self._py = _PyServer(port)
            self.port = self._py.port

    def stop(self):
        if self._l is not None:
            if self._h:
                self._l.tcp_store_server_stop(self._h)
                self._h = None
        elif self._py is not None:
            self._py.stop()


class TCPStore:
    """Client. host_is_master spawns the in-process server (rank 0)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, timeout: float = 30.0,
                 world_size: Optional[int] = None):
        self.server = TCPStoreServer(port) if is_master else None
        real_port = self.server.port if self.server else port
        self.host, self.port = host, real_port
        l = lib()
        self._l = l
        if l is not None:
            self._h = l.tcp_store_client_connect(
                host.encode(), real_port, int(timeout * 1000))
            if not self._h:
                raise TimeoutError(
                    f"TCPStore connect to {host}:{real_port} timed out")
        else:
            self._sock = _py_connect(host, real_port, timeout)

    def set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode()
        if self._l is not None:
            rc = self._l.tcp_store_set(self._h, key.encode(), value,
                                       len(value))
            if rc != 0:
                raise RuntimeError("TCPStore.set failed")
        else:
            _py_request(self._sock, 0, key, value)

    _CAP0 = 1 << 20

    def _fetch(self, fn, key, *pre_args):
        """Call a native get/wait entry point, growing the buffer when the
        value exceeds it (the C side returns the FULL length)."""
        cap = self._CAP0
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = fn(self._h, key.encode(), *pre_args, buf, cap)
            if n >= 0 and n <= cap:
                return buf.raw[:n]
            if n > cap:
                cap = n
                continue
            return n  # negative status

    def get(self, key: str) -> bytes:
        if self._l is not None:
            out = self._fetch(self._l.tcp_store_get, key)
            if out == -1:
                raise KeyError(key)
            if isinstance(out, int):
                raise RuntimeError("TCPStore.get io error")
            return out
        st, val = _py_request(self._sock, 1, key, b"")
        if st != 0:
            raise KeyError(key)
        return val

    def delete(self, key: str):
        if self._l is not None:
            self._l.tcp_store_delete(self._h, key.encode())
        else:
            _py_request(self._sock, 5, key, b"")

    def add(self, key: str, delta: int = 1) -> int:
        if self._l is not None:
            return int(self._l.tcp_store_add(self._h, key.encode(), delta))
        _, val = _py_request(self._sock, 2, key, str(delta).encode())
        return int(val)

    def wait(self, key: str, timeout: float = 30.0) -> bytes:
        if self._l is not None:
            out = self._fetch(self._l.tcp_store_wait, key,
                              int(timeout * 1000))
            if out == -1:
                raise TimeoutError(f"TCPStore.wait({key}) timed out")
            if isinstance(out, int):
                raise RuntimeError("TCPStore.wait io error")
            return out
        st, val = _py_request(self._sock, 3, key,
                              str(int(timeout * 1000)).encode())
        if st != 0:
            raise TimeoutError(f"TCPStore.wait({key}) timed out")
        return val

    def barrier(self, name: str, world_size: int, timeout: float = 60.0):
        n = self.add(f"__barrier/{name}", 1)
        if n == world_size:
            self.set(f"__barrier/{name}/done", b"1")
        self.wait(f"__barrier/{name}/done", timeout)

    def close(self):
        if self._l is not None and getattr(self, "_h", None):
            self._l.tcp_store_client_close(self._h)
            self._h = None
        if self.server is not None:
            self.server.stop()
            self.server = None


# ---------------- pure-python fallback (same wire format) ----------------
import socket
import struct


def _py_connect(host, port, timeout):
    deadline = time.time() + timeout
    while True:
        try:
            s = socket.create_connection((host, port), timeout=2)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s
        except OSError:
            if time.time() > deadline:
                raise TimeoutError(f"connect {host}:{port}")
            time.sleep(0.05)


def _recv_full(s, n):
    out = b""
    while len(out) < n:
        chunk = s.recv(n - len(out))
        if not chunk:
            raise ConnectionError("store closed")
        out += chunk
    return out


def _py_request(s, op, key, val):
    k = key.encode()
    s.sendall(struct.pack("<BI", op, len(k)) + k +
              struct.pack("<Q", len(val)) + val)
    status = _recv_full(s, 1)[0]
    (rlen,) = struct.unpack("<Q", _recv_full(s, 8))
    data = _recv_full(s, rlen) if rlen else b""
    return status, data


class _PyServer:
    def __init__(self, port=0):
        self.data = {}
        self.cv = threading.Condition()
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("0.0.0.0", port))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(128)
        self._stop = False
        self.thread = threading.Thread(target=self._accept, daemon=True)
        self.thread.start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                hdr = _recv_full(conn, 5)
                op, klen = struct.unpack("<BI", hdr)
                key = _recv_full(conn, klen).decode()
                (vlen,) = struct.unpack("<Q", _recv_full(conn, 8))
                val = _recv_full(conn, vlen) if vlen else b""
                if op == 0:
                    with self.cv:
                        self.data[key] = val
                        self.cv.notify_all()
                    reply = (0, b"")
                elif op == 1:
                    with self.cv:
                        reply = (0, self.data[key]) if key in self.data \
                            else (1, b"")
                elif op == 2:
                    with self.cv:
                        cur = int(self.data.get(key, b"0")) + int(val)
                        self.data[key] = str(cur).encode()
                        self.cv.notify_all()
                        reply = (0, self.data[key])
                elif op == 5:
                    with self.cv:
                        self.data.pop(key, None)
                    reply = (0, b"")
                elif op == 3:
                    tmo = int(val) / 1000.0
                    with self.cv:
                        ok = self.cv.wait_for(lambda: key in self.data, tmo)
                        reply = (0, self.data[key]) if ok else (1, b"")
                else:
                    reply = (1, b"")
                conn.sendall(bytes([reply[0]]) +
                             struct.pack("<Q", len(reply[1])) + reply[1])
        except (ConnectionError, OSError):
            pass

    def stop(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass
