// TCPStore: master/worker key-value rendezvous over raw TCP.
//
// Native equivalent of the reference's bootstrap store
// (/root/reference/paddle/phi/core/distributed/store/tcp_store.cc and
// tcp_utils.cc): one rank runs the master holding a map<string,string>;
// workers connect and issue SET/GET/WAIT/ADD ops. Used by the Python
// distributed bootstrap (paddle_tpu.distributed.env) the way the reference
// exchanges ncclUniqueId — here it carries jax.distributed coordinator
// addresses and barrier counters.
//
// Wire format: [1 byte op][u32 key_len][key][u64 val_len][val]
//   op: 0=SET 1=GET 2=ADD(i64 delta in val) 3=WAIT 4=COMPARE_SET 5=DELETE
// Reply: [u8 status][u64 val_len][val]   status: 0=ok 1=missing
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
};

int read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::read(fd, p + got, n - got);
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 0;
}

int write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t done = 0;
  while (done < n) {
    ssize_t r = ::write(fd, p + done, n - done);
    if (r <= 0) return -1;
    done += static_cast<size_t>(r);
  }
  return 0;
}

void reply(int fd, uint8_t status, const std::string& val) {
  uint64_t len = val.size();
  write_full(fd, &status, 1);
  write_full(fd, &len, 8);
  if (len) write_full(fd, val.data(), len);
}

struct Server {
  Store store;
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::vector<int> client_fds;
  std::mutex fds_mu;
  std::atomic<bool> stop{false};
  int port = 0;

  void handle(int fd) {
    for (;;) {
      uint8_t op;
      if (read_full(fd, &op, 1) != 0) break;
      uint32_t klen;
      if (read_full(fd, &klen, 4) != 0) break;
      std::string key(klen, '\0');
      if (klen && read_full(fd, key.data(), klen) != 0) break;
      uint64_t vlen;
      if (read_full(fd, &vlen, 8) != 0) break;
      std::string val(vlen, '\0');
      if (vlen && read_full(fd, val.data(), vlen) != 0) break;

      switch (op) {
        case 0: {  // SET
          std::lock_guard<std::mutex> g(store.mu);
          store.data[key] = val;
          store.cv.notify_all();
          reply(fd, 0, "");
          break;
        }
        case 1: {  // GET
          std::lock_guard<std::mutex> g(store.mu);
          auto it = store.data.find(key);
          if (it == store.data.end()) {
            reply(fd, 1, "");
          } else {
            reply(fd, 0, it->second);
          }
          break;
        }
        case 2: {  // ADD (val = ascii delta); returns new value
          int64_t delta = std::strtoll(val.c_str(), nullptr, 10);
          std::lock_guard<std::mutex> g(store.mu);
          int64_t cur = 0;
          auto it = store.data.find(key);
          if (it != store.data.end())
            cur = std::strtoll(it->second.c_str(), nullptr, 10);
          cur += delta;
          store.data[key] = std::to_string(cur);
          store.cv.notify_all();
          reply(fd, 0, store.data[key]);
          break;
        }
        case 3: {  // WAIT until key exists (val = timeout ms, ascii)
          int64_t timeout_ms = std::strtoll(val.c_str(), nullptr, 10);
          std::unique_lock<std::mutex> g(store.mu);
          bool ok = store.cv.wait_for(
              g, std::chrono::milliseconds(timeout_ms),
              [&] { return store.data.count(key) > 0; });
          if (ok) {
            reply(fd, 0, store.data[key]);
          } else {
            reply(fd, 1, "");
          }
          break;
        }
        case 4: {  // COMPARE_SET: val = expected \0 desired
          size_t sep = val.find('\0');
          std::string expected = val.substr(0, sep);
          std::string desired = val.substr(sep + 1);
          std::lock_guard<std::mutex> g(store.mu);
          auto it = store.data.find(key);
          std::string cur = (it == store.data.end()) ? "" : it->second;
          if (cur == expected) {
            store.data[key] = desired;
            store.cv.notify_all();
            reply(fd, 0, desired);
          } else {
            reply(fd, 1, cur);
          }
          break;
        }
        case 5: {  // DELETE
          std::lock_guard<std::mutex> g(store.mu);
          store.data.erase(key);
          reply(fd, 0, "");
          break;
        }
        default:
          reply(fd, 1, "");
      }
    }
    // forget this fd BEFORE closing it: after close the kernel can hand
    // the same fd number to a new accept(), and an erase-by-value would
    // then remove the new connection's entry (leaving shutdown() blind
    // to it) or shutdown() an unrelated descriptor
    {
      std::lock_guard<std::mutex> g(fds_mu);
      for (auto it = client_fds.begin(); it != client_fds.end(); ++it) {
        if (*it == fd) {
          client_fds.erase(it);
          break;
        }
      }
    }
    ::close(fd);
  }

  int start(int want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return -1;
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, 128) != 0) return -1;
    accept_thread = std::thread([this] {
      while (!stop.load()) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) break;
        int one2 = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one2, sizeof(one2));
        {
          std::lock_guard<std::mutex> g(fds_mu);
          client_fds.push_back(fd);
        }
        workers.emplace_back([this, fd] { handle(fd); });
      }
    });
    return port;
  }

  void shutdown() {
    stop.store(true);
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    if (accept_thread.joinable()) accept_thread.join();
    // unblock handler threads still parked in read_full() on live client
    // connections (e.g. rank 0 stopping while peers stay connected) —
    // without this the joins below hang until every client disconnects
    {
      std::lock_guard<std::mutex> g(fds_mu);
      for (int fd : client_fds) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;
  std::string last;

  int connect_to(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    for (;;) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      ::inet_pton(AF_INET, host, &addr.sin_addr);
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
          0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return 0;
      }
      ::close(fd);
      fd = -1;
      if (std::chrono::steady_clock::now() > deadline) return -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }

  // returns status; stores value into this->last
  int request(uint8_t op, const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu);
    uint32_t klen = key.size();
    uint64_t vlen = val.size();
    if (write_full(fd, &op, 1) || write_full(fd, &klen, 4) ||
        (klen && write_full(fd, key.data(), klen)) ||
        write_full(fd, &vlen, 8) ||
        (vlen && write_full(fd, val.data(), vlen)))
      return -1;
    uint8_t status;
    uint64_t rlen;
    if (read_full(fd, &status, 1) || read_full(fd, &rlen, 8)) return -1;
    last.resize(rlen);
    if (rlen && read_full(fd, last.data(), rlen)) return -1;
    return status;
  }
};

}  // namespace

extern "C" {

void* tcp_store_server_start(int port) {
  auto* s = new Server();
  int got = s->start(port);
  if (got < 0) {
    delete s;
    return nullptr;
  }
  return s;
}

int tcp_store_server_port(void* h) { return static_cast<Server*>(h)->port; }

void tcp_store_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->shutdown();
  delete s;
}

void* tcp_store_client_connect(const char* host, int port, int timeout_ms) {
  auto* c = new Client();
  if (c->connect_to(host, port, timeout_ms) != 0) {
    delete c;
    return nullptr;
  }
  return c;
}

void tcp_store_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

int tcp_store_set(void* h, const char* key, const char* val, int vlen) {
  return static_cast<Client*>(h)->request(0, key, std::string(val, vlen));
}

// Returns the FULL value length (even when > cap, so callers can detect
// truncation and refetch with a bigger buffer), or -1 missing / -2 io
// error. Copies min(len, cap) bytes into buf.
int tcp_store_get(void* h, const char* key, char* buf, int cap) {
  auto* c = static_cast<Client*>(h);
  int st = c->request(1, key, "");
  if (st != 0) return st == 1 ? -1 : -2;
  int n = static_cast<int>(c->last.size());
  std::memcpy(buf, c->last.data(), n > cap ? cap : n);
  return n;
}

int tcp_store_delete(void* h, const char* key) {
  return static_cast<Client*>(h)->request(5, key, "");
}

long long tcp_store_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<Client*>(h);
  int st = c->request(2, key, std::to_string(delta));
  if (st != 0) return -1;
  return std::strtoll(c->last.c_str(), nullptr, 10);
}

// Same truncation contract as tcp_store_get: returns the full length.
int tcp_store_wait(void* h, const char* key, int timeout_ms, char* buf,
                   int cap) {
  auto* c = static_cast<Client*>(h);
  int st = c->request(3, key, std::to_string(timeout_ms));
  if (st != 0) return st == 1 ? -1 : -2;
  int n = static_cast<int>(c->last.size());
  std::memcpy(buf, c->last.data(), n > cap ? cap : n);
  return n;
}

}  // extern "C"
