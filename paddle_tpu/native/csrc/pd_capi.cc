// C serving ABI — the reference's capi_exp surface over the TPU-native
// Predictor (reference: paddle/fluid/inference/capi_exp/pd_config.h,
// pd_predictor.h, pd_tensor.h; implemented there over AnalysisPredictor,
// here over paddle_tpu.inference via an embedded CPython interpreter —
// the XLA executable IS the inference engine, the C ABI is the serving
// shell, exactly as capi_exp shells the C++ predictor).
//
// Build: paddle_tpu.native.build_capi() → libpaddle_inference_c.so
// Host app contract: set PYTHONPATH so `import paddle_tpu` resolves
// (and JAX_PLATFORMS if a specific backend is wanted) before the first
// PD_PredictorCreate.
//
// Memory discipline mirrors the reference's __pd_give/__pd_keep:
// *Create/GetInputHandle/GetOutputHandle/GetInputNames give ownership,
// released with the matching *Destroy.

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

// the PUBLIC header is the single source of truth for the ABI — any
// signature drift between it and these definitions is a compile error
#include "pd_inference_c.h"

// opaque types from the header, defined here
struct PD_Config {
  std::string model_path;
  std::string params_path;
};

struct PD_Predictor {
  PyObject* pred;  // paddle_tpu.inference.Predictor
};

struct PD_Tensor {
  PyObject* handle;  // paddle_tpu.inference.Tensor
};

static std::mutex g_init_mu;
static bool g_we_initialized = false;

static void ensure_python() {
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    // release the GIL the init thread holds, or PyGILState_Ensure from
    // any OTHER host thread (the norm in a serving shell) deadlocks
    PyEval_SaveThread();
  }
}

// Run fn with the GIL held (works both embedded and when the host app
// is itself a Python process that loaded us via ctypes).
template <typename F>
static auto with_gil(F fn) -> decltype(fn()) {
  ensure_python();
  PyGILState_STATE st = PyGILState_Ensure();
  auto out = fn();
  PyGILState_Release(st);
  return out;
}

static void print_and_clear() {
  if (PyErr_Occurred()) PyErr_Print();
}

static PyObject* inference_module() {
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) print_and_clear();
  return mod;
}

// ----------------------------------------------------------- Config

PD_Config* PD_ConfigCreate() { return new PD_Config(); }

void PD_ConfigDestroy(PD_Config* c) { delete c; }

void PD_ConfigSetModel(PD_Config* c, const char* model,
                       const char* params) {
  c->model_path = model ? model : "";
  c->params_path = params ? params : "";
}

void PD_ConfigSetProgFile(PD_Config* c, const char* model) {
  c->model_path = model ? model : "";
}

void PD_ConfigSetParamsFile(PD_Config* c, const char* params) {
  c->params_path = params ? params : "";
}

const char* PD_ConfigGetProgFile(PD_Config* c) {
  return c->model_path.c_str();
}

const char* PD_ConfigGetParamsFile(PD_Config* c) {
  return c->params_path.c_str();
}

// -------------------------------------------------------- Predictor

PD_Predictor* PD_PredictorCreate(PD_Config* config) {
  return with_gil([&]() -> PD_Predictor* {
    PyObject* mod = inference_module();
    if (!mod) return nullptr;
    PyObject* cfg = nullptr;
    if (!config->params_path.empty()) {
      cfg = PyObject_CallMethod(mod, "Config", "ss",
                                config->model_path.c_str(),
                                config->params_path.c_str());
    } else {
      cfg = PyObject_CallMethod(mod, "Config", "s",
                                config->model_path.c_str());
    }
    if (!cfg) { print_and_clear(); Py_DECREF(mod); return nullptr; }
    PyObject* pred = PyObject_CallMethod(mod, "create_predictor", "O", cfg);
    Py_DECREF(cfg);
    Py_DECREF(mod);
    if (!pred) { print_and_clear(); return nullptr; }
    // route through the serving layer: with FLAGS_serving_capi_batching
    // enabled, wrap_capi returns a facade whose run() submits to a
    // shared dynamic-batching InferenceServer (C hosts get request
    // coalescing for free); otherwise it returns pred unchanged.
    PyObject* sv = PyImport_ImportModule("paddle_tpu.serving");
    if (sv) {
      PyObject* wrapped = PyObject_CallMethod(sv, "wrap_capi", "O", pred);
      if (wrapped) {
        Py_DECREF(pred);
        pred = wrapped;
      } else {
        PyErr_Clear();  // serving-layer failure degrades to plain pred
      }
      Py_DECREF(sv);
    } else {
      PyErr_Clear();
    }
    PD_Predictor* out = new PD_Predictor();
    out->pred = pred;
    return out;
  });
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (!p) return;
  with_gil([&]() -> int { Py_XDECREF(p->pred); return 0; });
  delete p;
}

static PD_OneDimArrayCstr* names_from_list(PyObject* list) {
  if (!list) { print_and_clear(); return nullptr; }
  if (!PyList_Check(list)) {
    // PyList_Size on a non-list returns -1, which would wrap around in
    // arr->size (size_t) and make new char*[-1] UB
    fprintf(stderr,
            "paddle_tpu capi: expected a list of names, got %s\n",
            Py_TYPE(list)->tp_name);
    Py_DECREF(list);
    return nullptr;
  }
  Py_ssize_t n = PyList_Size(list);
  PD_OneDimArrayCstr* arr = new PD_OneDimArrayCstr();
  arr->size = static_cast<size_t>(n);
  arr->data = new char*[n];
  for (Py_ssize_t i = 0; i < n; i++) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    arr->data[i] = strdup(s ? s : "");
  }
  Py_DECREF(list);
  return arr;
}

PD_OneDimArrayCstr* PD_PredictorGetInputNames(PD_Predictor* p) {
  return with_gil([&]() {
    return names_from_list(
        PyObject_CallMethod(p->pred, "get_input_names", nullptr));
  });
}

PD_OneDimArrayCstr* PD_PredictorGetOutputNames(PD_Predictor* p) {
  return with_gil([&]() {
    return names_from_list(
        PyObject_CallMethod(p->pred, "get_output_names", nullptr));
  });
}

void PD_OneDimArrayCstrDestroy(PD_OneDimArrayCstr* arr) {
  if (!arr) return;
  for (size_t i = 0; i < arr->size; i++) free(arr->data[i]);
  delete[] arr->data;
  delete arr;
}

size_t PD_PredictorGetInputNum(PD_Predictor* p) {
  PD_OneDimArrayCstr* n = PD_PredictorGetInputNames(p);
  size_t out = n ? n->size : 0;
  PD_OneDimArrayCstrDestroy(n);
  return out;
}

size_t PD_PredictorGetOutputNum(PD_Predictor* p) {
  PD_OneDimArrayCstr* n = PD_PredictorGetOutputNames(p);
  size_t out = n ? n->size : 0;
  PD_OneDimArrayCstrDestroy(n);
  return out;
}

static PD_Tensor* tensor_from(PyObject* h) {
  if (!h) { print_and_clear(); return nullptr; }
  PD_Tensor* t = new PD_Tensor();
  t->handle = h;
  return t;
}

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name) {
  return with_gil([&]() {
    return tensor_from(
        PyObject_CallMethod(p->pred, "get_input_handle", "s", name));
  });
}

PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name) {
  return with_gil([&]() {
    return tensor_from(
        PyObject_CallMethod(p->pred, "get_output_handle", "s", name));
  });
}

PD_Bool PD_PredictorRun(PD_Predictor* p) {
  return with_gil([&]() -> PD_Bool {
    PyObject* r = PyObject_CallMethod(p->pred, "run", nullptr);
    if (!r) { print_and_clear(); return 0; }
    PD_Bool ok = PyObject_IsTrue(r) ? 1 : 0;
    Py_DECREF(r);
    return ok;
  });
}

void PD_PredictorClearIntermediateTensor(PD_Predictor* p) {
  with_gil([&]() -> int {
    PyObject* r = PyObject_CallMethod(p->pred,
                                      "clear_intermediate_tensor", nullptr);
    Py_XDECREF(r);
    return 0;
  });
}

// ----------------------------------------------------------- Tensor

void PD_TensorDestroy(PD_Tensor* t) {
  if (!t) return;
  with_gil([&]() -> int { Py_XDECREF(t->handle); return 0; });
  delete t;
}

void PD_TensorReshape(PD_Tensor* t, size_t shape_size, int32_t* shape) {
  with_gil([&]() -> int {
    PyObject* lst = PyList_New(shape_size);
    for (size_t i = 0; i < shape_size; i++)
      PyList_SetItem(lst, i, PyLong_FromLong(shape[i]));
    PyObject* r = PyObject_CallMethod(t->handle, "reshape", "O", lst);
    Py_DECREF(lst);
    if (!r) print_and_clear();
    Py_XDECREF(r);
    return 0;
  });
}

PD_OneDimArrayInt32* PD_TensorGetShape(PD_Tensor* t) {
  return with_gil([&]() -> PD_OneDimArrayInt32* {
    PyObject* shape = PyObject_GetAttrString(t->handle, "shape");
    if (!shape || shape == Py_None) {
      Py_XDECREF(shape);
      print_and_clear();
      return nullptr;
    }
    Py_ssize_t n = PySequence_Size(shape);
    PD_OneDimArrayInt32* arr = new PD_OneDimArrayInt32();
    arr->size = static_cast<size_t>(n);
    arr->data = new int32_t[n];
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* it = PySequence_GetItem(shape, i);
      arr->data[i] = static_cast<int32_t>(PyLong_AsLong(it));
      Py_DECREF(it);
    }
    Py_DECREF(shape);
    return arr;
  });
}

void PD_OneDimArrayInt32Destroy(PD_OneDimArrayInt32* arr) {
  if (!arr) return;
  delete[] arr->data;
  delete arr;
}

static const char* np_dtype_for(int pd_dtype) {
  switch (pd_dtype) {
    case PD_DATA_FLOAT32: return "float32";
    case PD_DATA_INT32: return "int32";
    case PD_DATA_INT64: return "int64";
    case PD_DATA_UINT8: return "uint8";
    case PD_DATA_INT8: return "int8";
  }
  return nullptr;
}

static size_t dtype_size(int pd_dtype) {
  switch (pd_dtype) {
    case PD_DATA_FLOAT32: case PD_DATA_INT32: return 4;
    case PD_DATA_INT64: return 8;
    default: return 1;
  }
}

// copy_from: build a numpy array from the C buffer using the handle's
// current shape (set via PD_TensorReshape first — the capi_exp flow).
static void copy_from_cpu(PD_Tensor* t, const void* data, int pd_dtype) {
  with_gil([&]() -> int {
    PyObject* np = PyImport_ImportModule("numpy");
    if (!np) { print_and_clear(); return 0; }
    PyObject* shape = PyObject_GetAttrString(t->handle, "shape");
    if (!shape || shape == Py_None) {
      // diagnose instead of silently no-opping: the C caller would
      // otherwise run inference on stale/zero inputs with no signal
      fprintf(stderr,
              "paddle_tpu capi: PD_TensorCopyFromCpu* on a handle with "
              "no shape — call PD_TensorReshape first (the capi_exp "
              "Reshape -> CopyFromCpu flow); the copy was skipped\n");
      print_and_clear();
      Py_XDECREF(shape);
      Py_DECREF(np);
      PyErr_Clear();
      return 0;
    }
    // numel from shape
    Py_ssize_t n = PySequence_Size(shape);
    size_t numel = 1;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* it = PySequence_GetItem(shape, i);
      numel *= static_cast<size_t>(PyLong_AsLong(it));
      Py_DECREF(it);
    }
    PyObject* buf = PyBytes_FromStringAndSize(
        static_cast<const char*>(data), numel * dtype_size(pd_dtype));
    PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", buf,
                                         np_dtype_for(pd_dtype));
    PyObject* arr = flat ? PyObject_CallMethod(flat, "reshape", "O", shape)
                         : nullptr;
    if (arr) {
      PyObject* r = PyObject_CallMethod(t->handle, "copy_from_cpu", "O",
                                        arr);
      if (!r) print_and_clear();
      Py_XDECREF(r);
      Py_DECREF(arr);
    } else {
      print_and_clear();
    }
    Py_XDECREF(flat);
    Py_XDECREF(buf);
    Py_DECREF(shape);
    Py_DECREF(np);
    return 0;
  });
}

static void copy_to_cpu(PD_Tensor* t, void* data, int pd_dtype) {
  with_gil([&]() -> int {
    PyObject* arr = PyObject_CallMethod(t->handle, "copy_to_cpu", nullptr);
    if (!arr) { print_and_clear(); return 0; }
    PyObject* np = PyImport_ImportModule("numpy");
    PyObject* cast = PyObject_CallMethod(
        np, "ascontiguousarray", "Os", arr, np_dtype_for(pd_dtype));
    if (cast) {
      PyObject* bytes = PyObject_CallMethod(cast, "tobytes", nullptr);
      if (bytes) {
        char* src = nullptr;
        Py_ssize_t len = 0;
        PyBytes_AsStringAndSize(bytes, &src, &len);
        memcpy(data, src, static_cast<size_t>(len));
        Py_DECREF(bytes);
      }
      Py_DECREF(cast);
    } else {
      print_and_clear();
    }
    Py_DECREF(np);
    Py_DECREF(arr);
    return 0;
  });
}

void PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data) {
  copy_from_cpu(t, data, PD_DATA_FLOAT32);
}
void PD_TensorCopyFromCpuInt32(PD_Tensor* t, const int32_t* data) {
  copy_from_cpu(t, data, PD_DATA_INT32);
}
void PD_TensorCopyFromCpuInt64(PD_Tensor* t, const int64_t* data) {
  copy_from_cpu(t, data, PD_DATA_INT64);
}
void PD_TensorCopyFromCpuInt8(PD_Tensor* t, const int8_t* data) {
  copy_from_cpu(t, data, PD_DATA_INT8);
}
void PD_TensorCopyFromCpuUint8(PD_Tensor* t, const uint8_t* data) {
  copy_from_cpu(t, data, PD_DATA_UINT8);
}

void PD_TensorCopyToCpuFloat(PD_Tensor* t, float* data) {
  copy_to_cpu(t, data, PD_DATA_FLOAT32);
}
void PD_TensorCopyToCpuInt32(PD_Tensor* t, int32_t* data) {
  copy_to_cpu(t, data, PD_DATA_INT32);
}
void PD_TensorCopyToCpuInt64(PD_Tensor* t, int64_t* data) {
  copy_to_cpu(t, data, PD_DATA_INT64);
}
void PD_TensorCopyToCpuInt8(PD_Tensor* t, int8_t* data) {
  copy_to_cpu(t, data, PD_DATA_INT8);
}
void PD_TensorCopyToCpuUint8(PD_Tensor* t, uint8_t* data) {
  copy_to_cpu(t, data, PD_DATA_UINT8);
}

int32_t PD_TensorGetDataType(PD_Tensor* t) {
  return with_gil([&]() -> int32_t {
    PyObject* r = PyObject_CallMethod(t->handle, "type", nullptr);
    if (!r || r == Py_None) { Py_XDECREF(r); PyErr_Clear();
                              return PD_DATA_UNK; }
    const char* s = PyUnicode_AsUTF8(r);
    int32_t out = PD_DATA_UNK;
    if (s) {
      if (!strcmp(s, "float32")) out = PD_DATA_FLOAT32;
      else if (!strcmp(s, "int32")) out = PD_DATA_INT32;
      else if (!strcmp(s, "int64")) out = PD_DATA_INT64;
      else if (!strcmp(s, "uint8")) out = PD_DATA_UINT8;
      else if (!strcmp(s, "int8")) out = PD_DATA_INT8;
    }
    Py_DECREF(r);
    return out;
  });
}

const char* PD_GetVersion(void) {
  // one-shot: concurrent callers must not race on (or dangle into) a
  // mutating buffer; the process-lifetime string never changes
  static std::once_flag once;
  static std::string version;
  std::call_once(once, []() {
    with_gil([&]() -> int {
      PyObject* mod = inference_module();
      if (!mod) return 0;
      PyObject* r = PyObject_CallMethod(mod, "get_version", nullptr);
      Py_DECREF(mod);
      if (!r) { print_and_clear(); return 0; }
      const char* s = PyUnicode_AsUTF8(r);
      version = s ? s : "";
      Py_DECREF(r);
      return 0;
    });
  });
  return version.c_str();
}
