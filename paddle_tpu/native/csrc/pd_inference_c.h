/* C serving ABI for paddle_tpu — the reference capi_exp surface
 * (paddle/fluid/inference/capi_exp/pd_inference_api.h) over the
 * TPU-native Predictor. Link against libpaddle_inference_c.so (built by
 * paddle_tpu.native.build_capi()); set PYTHONPATH so `import paddle_tpu`
 * resolves before the first PD_PredictorCreate.
 *
 * Ownership follows the reference's __pd_give convention: everything a
 * *Create/Get*Handle/Get*Names call returns is released with the
 * matching *Destroy. */
#ifndef PADDLE_TPU_PD_INFERENCE_C_H_
#define PADDLE_TPU_PD_INFERENCE_C_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int32_t PD_Bool;
typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;

typedef struct PD_OneDimArrayCstr {
  size_t size;
  char** data;
} PD_OneDimArrayCstr;

typedef struct PD_OneDimArrayInt32 {
  size_t size;
  int32_t* data;
} PD_OneDimArrayInt32;

/* PD_DataType (pd_types.h subset) */
enum { PD_DATA_UNK = -1, PD_DATA_FLOAT32 = 0, PD_DATA_INT32 = 2,
       PD_DATA_INT64 = 3, PD_DATA_UINT8 = 4, PD_DATA_INT8 = 5 };

PD_Config* PD_ConfigCreate(void);
void PD_ConfigDestroy(PD_Config* c);
void PD_ConfigSetModel(PD_Config* c, const char* model_path,
                       const char* params_path);
void PD_ConfigSetProgFile(PD_Config* c, const char* model_path);
void PD_ConfigSetParamsFile(PD_Config* c, const char* params_path);
const char* PD_ConfigGetProgFile(PD_Config* c);
const char* PD_ConfigGetParamsFile(PD_Config* c);

PD_Predictor* PD_PredictorCreate(PD_Config* config);
void PD_PredictorDestroy(PD_Predictor* p);
PD_OneDimArrayCstr* PD_PredictorGetInputNames(PD_Predictor* p);
PD_OneDimArrayCstr* PD_PredictorGetOutputNames(PD_Predictor* p);
size_t PD_PredictorGetInputNum(PD_Predictor* p);
size_t PD_PredictorGetOutputNum(PD_Predictor* p);
PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name);
PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name);
PD_Bool PD_PredictorRun(PD_Predictor* p);
void PD_PredictorClearIntermediateTensor(PD_Predictor* p);
void PD_OneDimArrayCstrDestroy(PD_OneDimArrayCstr* arr);

void PD_TensorDestroy(PD_Tensor* t);
void PD_TensorReshape(PD_Tensor* t, size_t shape_size, int32_t* shape);
PD_OneDimArrayInt32* PD_TensorGetShape(PD_Tensor* t);
void PD_OneDimArrayInt32Destroy(PD_OneDimArrayInt32* arr);
int32_t PD_TensorGetDataType(PD_Tensor* t);

void PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data);
void PD_TensorCopyFromCpuInt32(PD_Tensor* t, const int32_t* data);
void PD_TensorCopyFromCpuInt64(PD_Tensor* t, const int64_t* data);
void PD_TensorCopyFromCpuInt8(PD_Tensor* t, const int8_t* data);
void PD_TensorCopyFromCpuUint8(PD_Tensor* t, const uint8_t* data);
void PD_TensorCopyToCpuFloat(PD_Tensor* t, float* data);
void PD_TensorCopyToCpuInt32(PD_Tensor* t, int32_t* data);
void PD_TensorCopyToCpuInt64(PD_Tensor* t, int64_t* data);
void PD_TensorCopyToCpuInt8(PD_Tensor* t, int8_t* data);
void PD_TensorCopyToCpuUint8(PD_Tensor* t, uint8_t* data);

const char* PD_GetVersion(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_PD_INFERENCE_C_H_ */
