// Host tracer: lock-free-ish span recorder with chrome-trace export.
//
// Native equivalent of the reference profiler's HostTracer
// (/root/reference/paddle/fluid/platform/profiler/host_tracer.h:26 and
// chrometracing_logger.cc): RecordEvent spans are pushed from any thread
// into per-thread buffers; stop() merges and dumps chrome://tracing JSON.
// The Python profiler (paddle_tpu.profiler) drives this via ctypes and
// composes it with jax.profiler for device (XLA) activity.

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  std::string name;
  uint64_t begin_ns;
  uint64_t end_ns;
  int64_t tid;
};

struct Tracer {
  std::vector<Event> events;
  std::mutex mu;
  std::atomic<bool> enabled{false};
  uint64_t start_ns = 0;
};

Tracer g_tracer;

uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

void host_tracer_start() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.events.clear();
  g_tracer.start_ns = now_ns();
  g_tracer.enabled.store(true);
}

int host_tracer_enabled() { return g_tracer.enabled.load() ? 1 : 0; }

uint64_t host_tracer_now() { return now_ns(); }

void host_tracer_record(const char* name, uint64_t begin_ns,
                        uint64_t end_ns) {
  if (!g_tracer.enabled.load()) return;
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.events.push_back(
      Event{name, begin_ns, end_ns,
            static_cast<int64_t>(::syscall(SYS_gettid))});
}

int host_tracer_event_count() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  return static_cast<int>(g_tracer.events.size());
}

// Stop and write chrome-trace JSON to path. Returns #events or -1.
int host_tracer_stop(const char* path) {
  g_tracer.enabled.store(false);
  std::lock_guard<std::mutex> g(g_tracer.mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[", f);
  bool first = true;
  for (const auto& e : g_tracer.events) {
    if (!first) std::fputc(',', f);
    first = false;
    // chrome trace wants microseconds
    std::fprintf(f,
                 "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%lld,"
                 "\"ts\":%.3f,\"dur\":%.3f}",
                 e.name.c_str(), static_cast<int>(::getpid()),
                 static_cast<long long>(e.tid),
                 (e.begin_ns - g_tracer.start_ns) / 1000.0,
                 (e.end_ns - e.begin_ns) / 1000.0);
  }
  std::fputs("]}", f);
  std::fclose(f);
  return static_cast<int>(g_tracer.events.size());
}

}  // extern "C"
