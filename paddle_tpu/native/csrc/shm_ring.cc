// Shared-memory ring buffer for multiprocess DataLoader batch transport.
//
// Native equivalent of the reference's shared-memory DataLoader path
// (/root/reference/paddle/fluid/memory/allocation/mmap_allocator.cc and
// imperative/data_loader.cc): worker processes serialize sample arrays into
// a POSIX shm ring; the trainer process consumes without pickling overhead.
// Slot layout: [u64 payload_len][payload]; ring header holds head/tail
// indices and slot geometry, synchronized with atomics + futex-free
// spin/yield (batches are large, contention is low).

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;  // next slot to write
  std::atomic<uint64_t> tail;  // next slot to read
  uint64_t n_slots;
  uint64_t slot_bytes;
  std::atomic<int32_t> closed;
};

struct Ring {
  RingHeader* hdr = nullptr;
  char* slots = nullptr;
  size_t total = 0;
  std::string name;
  bool owner = false;
};

char* slot_ptr(Ring* r, uint64_t idx) {
  return r->slots + (idx % r->hdr->n_slots) * r->hdr->slot_bytes;
}

}  // namespace

extern "C" {

// Create (owner=1) or open (owner=0) a ring of n_slots x slot_bytes.
void* shm_ring_open(const char* name, int owner, uint64_t n_slots,
                    uint64_t slot_bytes) {
  size_t total = sizeof(RingHeader) + n_slots * slot_bytes;
  int fd;
  if (owner) {
    ::shm_unlink(name);
    fd = ::shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
    if (fd < 0) return nullptr;
    if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
      ::close(fd);
      return nullptr;
    }
  } else {
    fd = ::shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st {};
    ::fstat(fd, &st);
    total = static_cast<size_t>(st.st_size);
  }
  void* mem =
      ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* r = new Ring();
  r->hdr = static_cast<RingHeader*>(mem);
  r->slots = static_cast<char*>(mem) + sizeof(RingHeader);
  r->total = total;
  r->name = name;
  r->owner = owner != 0;
  if (owner) {
    r->hdr->head.store(0);
    r->hdr->tail.store(0);
    r->hdr->n_slots = n_slots;
    r->hdr->slot_bytes = slot_bytes;
    r->hdr->closed.store(0);
  }
  return r;
}

// Push payload (blocks while full unless ring closed). 0 ok, -1 closed,
// -2 too large.
int shm_ring_push(void* h, const char* data, uint64_t len) {
  auto* r = static_cast<Ring*>(h);
  if (len + 8 > r->hdr->slot_bytes) return -2;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (r->hdr->closed.load()) return -1;
    if (head - tail < r->hdr->n_slots) {
      char* p = slot_ptr(r, head);
      std::memcpy(p, &len, 8);
      std::memcpy(p + 8, data, len);
      r->hdr->head.store(head + 1, std::memory_order_release);
      return 0;
    }
    ::sched_yield();
  }
}

// Pop into buf (cap bytes). Returns payload len, -1 if closed+empty,
// -2 if buf too small, -3 timeout. timeout_ms<0 → block forever.
long long shm_ring_pop(void* h, char* buf, uint64_t cap, int timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  int waited_us = 0;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (tail < head) {
      char* p = slot_ptr(r, tail);
      uint64_t len;
      std::memcpy(&len, p, 8);
      if (len > cap) return -2;
      std::memcpy(buf, p + 8, len);
      r->hdr->tail.store(tail + 1, std::memory_order_release);
      return static_cast<long long>(len);
    }
    if (r->hdr->closed.load()) return -1;
    if (timeout_ms >= 0 && waited_us > timeout_ms * 1000) return -3;
    ::usleep(200);
    waited_us += 200;
  }
}

uint64_t shm_ring_size(void* h) {
  auto* r = static_cast<Ring*>(h);
  return r->hdr->head.load() - r->hdr->tail.load();
}

void shm_ring_close(void* h) { static_cast<Ring*>(h)->hdr->closed.store(1); }

void shm_ring_free(void* h) {
  auto* r = static_cast<Ring*>(h);
  ::munmap(r->hdr, r->total);
  if (r->owner) ::shm_unlink(r->name.c_str());
  delete r;
}

}  // extern "C"
