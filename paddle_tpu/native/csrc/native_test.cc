// Native-layer unit tests (SURVEY §4.6: the reference ships colocated
// C++ gtests per library; no gtest is available in this image, so these
// are assert-style checks with a main() — built and run by
// tests/test_native_cc.py). Covers the TCPStore client/server protocol,
// the shm ring SPSC transport, and the host tracer event buffer.
//
// Build: g++ -O1 -std=c++17 -pthread native_test.cc tcp_store.cc \
//            shm_ring.cc host_tracer.cc -lrt -o native_test
#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {
void* tcp_store_server_start(int port);
int tcp_store_server_port(void* h);
void tcp_store_server_stop(void* h);
void* tcp_store_client_connect(const char* host, int port, int timeout_ms);
void tcp_store_client_close(void* h);
int tcp_store_set(void* h, const char* key, const char* val, int vlen);
int tcp_store_get(void* h, const char* key, char* buf, int cap);
int tcp_store_delete(void* h, const char* key);
long long tcp_store_add(void* h, const char* key, long long delta);
int tcp_store_wait(void* h, const char* key, int timeout_ms, char* buf,
                   int cap);

void* shm_ring_open(const char* name, int owner, uint64_t n_slots,
                    uint64_t slot_bytes);
int shm_ring_push(void* h, const char* data, uint64_t len);
long long shm_ring_pop(void* h, char* buf, uint64_t cap, int timeout_ms);
void shm_ring_close(void* h);
void shm_ring_free(void* h);

void host_tracer_start();
int host_tracer_enabled();
uint64_t host_tracer_now();
void host_tracer_record(const char* name, uint64_t begin_ns,
                        uint64_t end_ns);
int host_tracer_event_count();
int host_tracer_stop(const char* path);
}

static int tests_run = 0;
#define CHECK(cond)                                              \
  do {                                                           \
    if (!(cond)) {                                               \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__,         \
                   __LINE__, #cond);                             \
      return 1;                                                  \
    }                                                            \
  } while (0)

static int test_tcp_store() {
  ++tests_run;
  void* srv = tcp_store_server_start(0);  // ephemeral port
  CHECK(srv != nullptr);
  int port = tcp_store_server_port(srv);
  CHECK(port > 0);
  void* cli = tcp_store_client_connect("127.0.0.1", port, 2000);
  CHECK(cli != nullptr);

  CHECK(tcp_store_set(cli, "k", "hello", 5) == 0);
  char buf[64];
  CHECK(tcp_store_get(cli, "k", buf, sizeof buf) == 5);
  CHECK(std::memcmp(buf, "hello", 5) == 0);
  CHECK(tcp_store_get(cli, "missing", buf, sizeof buf) == -1);

  // truncation contract: full length returned even when cap is small
  std::string big(100, 'x');
  CHECK(tcp_store_set(cli, "big", big.data(), 100) == 0);
  char tiny[8];
  CHECK(tcp_store_get(cli, "big", tiny, 8) == 100);

  CHECK(tcp_store_add(cli, "ctr", 2) == 2);
  CHECK(tcp_store_add(cli, "ctr", 3) == 5);

  CHECK(tcp_store_delete(cli, "k") == 0);
  CHECK(tcp_store_get(cli, "k", buf, sizeof buf) == -1);

  // wait: a second client sets the key after a delay
  std::thread setter([port] {
    void* c2 = tcp_store_client_connect("127.0.0.1", port, 2000);
    if (!c2) return;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    tcp_store_set(c2, "later", "v", 1);
    tcp_store_client_close(c2);
  });
  int wait_rc = tcp_store_wait(cli, "later", 5000, buf, sizeof buf);
  setter.join();  // join BEFORE any CHECK can return with it joinable
  CHECK(wait_rc == 1);
  CHECK(buf[0] == 'v');

  tcp_store_client_close(cli);
  // server stop must not hang even though a client connected earlier
  tcp_store_server_stop(srv);
  return 0;
}

static int test_shm_ring() {
  ++tests_run;
  char name[64];
  std::snprintf(name, sizeof name, "/pt_native_test_ring_%d",
                static_cast<int>(::getpid()));
  void* w = shm_ring_open(name, 1, 4, 64);
  CHECK(w != nullptr);
  void* r = shm_ring_open(name, 0, 4, 64);
  CHECK(r != nullptr);

  CHECK(shm_ring_push(w, "abc", 3) == 0);
  char buf[64];
  CHECK(shm_ring_pop(r, buf, sizeof buf, 1000) == 3);
  CHECK(std::memcmp(buf, "abc", 3) == 0);

  // payload larger than a slot is rejected, not corrupted
  std::string big(200, 'y');
  CHECK(shm_ring_push(w, big.data(), big.size()) == -2);

  // wrap-around: push/pop more records than slots
  for (int i = 0; i < 10; ++i) {
    char msg[16];
    int n = std::snprintf(msg, sizeof msg, "m%d", i);
    CHECK(shm_ring_push(w, msg, n) == 0);
    long long got = shm_ring_pop(r, buf, sizeof buf, 1000);
    CHECK(got == n);
    CHECK(std::memcmp(buf, msg, n) == 0);
  }

  // pop on empty times out
  CHECK(shm_ring_pop(r, buf, sizeof buf, 10) == -3);

  // closed + empty -> -1 for consumers
  shm_ring_close(w);
  CHECK(shm_ring_pop(r, buf, sizeof buf, 1000) == -1);
  shm_ring_free(r);
  shm_ring_free(w);
  return 0;
}

static int test_host_tracer() {
  ++tests_run;
  host_tracer_start();
  CHECK(host_tracer_enabled() == 1);
  uint64_t t0 = host_tracer_now();
  host_tracer_record("evt_a", t0, t0 + 1000);
  host_tracer_record("evt_b", t0 + 2000, t0 + 3000);
  CHECK(host_tracer_event_count() == 2);
  char path[96];
  std::snprintf(path, sizeof path, "/tmp/pt_native_test_trace_%d.json",
                static_cast<int>(::getpid()));
  CHECK(host_tracer_stop(path) == 2);  // returns #events
  FILE* f = std::fopen(path, "rb");
  CHECK(f != nullptr);
  char content[4096];
  size_t n = std::fread(content, 1, sizeof content - 1, f);
  std::fclose(f);
  content[n] = 0;
  CHECK(std::strstr(content, "evt_a") != nullptr);
  CHECK(std::strstr(content, "evt_b") != nullptr);
  std::remove(path);
  return 0;
}

int main() {
  if (test_tcp_store()) return 1;
  if (test_shm_ring()) return 1;
  if (test_host_tracer()) return 1;
  std::printf("native_test: %d suites passed\n", tests_run);
  return 0;
}
