"""paddle_tpu — a TPU-native deep-learning framework with a Paddle-shaped API.

Capabilities mirror the PaddlePaddle reference (see SURVEY.md); the
implementation is idiomatic JAX/XLA/Pallas/pjit: ops lower to XLA, autograd is
jax.vjp-based, distributed training is mesh/sharding-first, kernels that need
hand-tuning are Pallas.
"""
from __future__ import annotations

import jax as _jax

# float64/int64 are first-class dtypes in the reference (VarType FP64/INT64,
# /root/reference/paddle/fluid/framework/framework.proto); enable them in XLA.
# Default dtypes for literals remain paddle-like (float32) — the Tensor
# constructor and creation ops pass explicit dtypes.
# NOTE: this is a process-wide jax setting; non-paddle jax code in the same
# process also gains 64-bit defaults (jnp.arange → int64 etc.). Framework
# call sites must therefore always pass explicit dtypes.
_jax.config.update("jax_enable_x64", True)

# Core types
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .core.autograd import enable_grad, grad  # noqa: F401
from .core.autograd import no_grad_decorator as _ngd

no_grad = _ngd()  # paddle.no_grad usable as decorator and context manager

# dtypes
from .framework.dtype import (  # noqa: F401
    bfloat16, bool_ as bool8, complex64, complex128, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
)
from .framework import dtype as _dtype_mod

dtype = _dtype_mod.DType

# places & device
from .framework.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace, Place, TPUPlace, XPUPlace,
)
from .framework.device import (  # noqa: F401
    device_count, get_device, is_compiled_with_cuda, is_compiled_with_rocm,
    is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)
from .framework.flags import flags_snapshot, get_flags, set_flags  # noqa: F401
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: F401
from .framework.misc import (  # noqa: F401
    LazyGuard, batch, check_shape, disable_signal_handler, finfo, flops,
    get_cuda_rng_state, iinfo, set_cuda_rng_state, set_grad_enabled,
    set_printoptions,
)
from .nn.initializer_utils import ParamAttr  # noqa: F401
from .framework.dtype import bool_ as bool  # noqa: F401,A001


# full functional tensor surface (also patches Tensor methods)
from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation  # noqa: F401

# subpackages (imported lazily below to keep import time low would be nicer,
# but paddle exposes them eagerly; mirror that)
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import audio  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import hub  # noqa: F401
from . import geometric  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from .hapi import callbacks  # noqa: F401
from . import distribution  # noqa: F401
from . import distributed  # noqa: F401
from . import compile_cache  # noqa: F401
from . import elastic  # noqa: F401
from . import framework  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import linalg  # noqa: F401
from . import observability  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import serving  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401

from .framework.io import load, save  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from . import regularizer  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary  # noqa: F401

__version__ = "0.1.0"


def is_grad_enabled():
    from .core.autograd import grad_enabled
    return grad_enabled()


def in_dynamic_mode():
    from .static.program import in_static_mode
    return not in_static_mode()


def enable_static():
    from .static.program import _enable_static
    _enable_static()


def disable_static():
    from .static.program import _disable_static
    _disable_static()


def synchronize():
    from .framework.device import synchronize as _sync
    _sync()
