"""Dtype system for paddle_tpu.

Paddle exposes dtypes both as objects (``paddle.float32``) and as strings
(``'float32'``). The reference implements this as ``VarType`` proto enums
(/root/reference/paddle/fluid/framework/framework.proto) plus conversion
helpers. Here dtypes are thin named wrappers over numpy/jax dtypes so they
interop directly with jax.numpy.
"""
from __future__ import annotations

import numpy as np

try:
    import ml_dtypes

    _BF16 = ml_dtypes.bfloat16
    _FP8_E4M3 = getattr(ml_dtypes, "float8_e4m3fn", None)
    _FP8_E5M2 = getattr(ml_dtypes, "float8_e5m2", None)
except ImportError:  # pragma: no cover
    _BF16 = None
    _FP8_E4M3 = None
    _FP8_E5M2 = None


class DType:
    """A framework dtype: named, hashable, convertible to numpy/jax dtype."""

    __slots__ = ("name", "np_dtype", "is_floating", "is_integer", "is_complex", "itemsize")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        kind = self.np_dtype.kind
        # bfloat16/fp8 are 'V'-kind in numpy terms under ml_dtypes unless
        # registered; test explicitly.
        self.is_floating = kind == "f" or name in ("bfloat16", "float8_e4m3fn", "float8_e5m2")
        self.is_integer = kind in ("i", "u")
        self.is_complex = kind == "c"
        self.itemsize = self.np_dtype.itemsize

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __str__(self):
        return self.name

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == _canon_name(other)
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented


def _canon_name(name: str) -> str:
    aliases = {
        "float": "float32",
        "double": "float64",
        "half": "float16",
        "int": "int32",
        "long": "int64",
        "bfloat": "bfloat16",
    }
    return aliases.get(name, name)


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

if _BF16 is not None:
    bfloat16 = DType("bfloat16", _BF16)
else:  # pragma: no cover
    bfloat16 = DType("bfloat16", np.float32)

if _FP8_E4M3 is not None:
    float8_e4m3fn = DType("float8_e4m3fn", _FP8_E4M3)
    float8_e5m2 = DType("float8_e5m2", _FP8_E5M2)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, float32, float64,
        complex64, complex128, bfloat16]
if _FP8_E4M3 is not None:
    _ALL += [float8_e4m3fn, float8_e5m2]

_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool"] = bool_
_BY_NPDTYPE = {d.np_dtype: d for d in reversed(_ALL)}


def convert_dtype(dtype) -> DType:
    """Coerce str / numpy dtype / DType / jax dtype into a framework DType."""
    if dtype is None:
        return None
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        name = _canon_name(dtype)
        if name in _BY_NAME:
            return _BY_NAME[name]
        raise ValueError(f"Unknown dtype string: {dtype!r}")
    npd = np.dtype(dtype)
    if npd in _BY_NPDTYPE:
        return _BY_NPDTYPE[npd]
    raise ValueError(f"Unsupported dtype: {dtype!r}")


def to_jax_dtype(dtype):
    """DType/str → numpy dtype usable by jax.numpy. None passes through."""
    if dtype is None:
        return None
    return convert_dtype(dtype).np_dtype


# Default dtype handling (paddle.set_default_dtype / get_default_dtype).
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not d.is_floating:
        raise TypeError(f"set_default_dtype only accepts floating dtypes, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name


def default_float_dtype() -> DType:
    return _default_dtype


def is_floating_dtype(dtype) -> bool:
    return convert_dtype(dtype).is_floating


_PROMOTE_ORDER = {
    "bool": 0, "uint8": 1, "int8": 2, "int16": 3, "int32": 4, "int64": 5,
    "float8_e4m3fn": 6, "float8_e5m2": 6, "float16": 7, "bfloat16": 7,
    "float32": 8, "float64": 9, "complex64": 10, "complex128": 11,
}


def promote_types(a, b) -> DType:
    """Simple type promotion mirroring jnp.promote_types for common cases."""
    a, b = convert_dtype(a), convert_dtype(b)
    if a == b:
        return a
    r = np.promote_types(a.np_dtype, b.np_dtype) if (
        a.name not in ("bfloat16",) and b.name not in ("bfloat16",)
    ) else None
    if r is not None:
        return convert_dtype(r)
    # bfloat16 promotion: bf16 + f16 → f32; bf16 + f32 → f32; bf16 + int → bf16
    other = b if a.name == "bfloat16" else a
    if other.is_integer or other.name == "bool":
        return bfloat16
    if other.name in ("float16",):
        return float32
    return other if _PROMOTE_ORDER[other.name] > _PROMOTE_ORDER["bfloat16"] else bfloat16
