from . import dtype, flags, place, random  # noqa: F401
from .dtype import (  # noqa: F401
    DType, convert_dtype, get_default_dtype, set_default_dtype, to_jax_dtype,
)
from .flags import flags_snapshot, get_flags, set_flags  # noqa: F401
from .place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, Place, TPUPlace, XPUPlace,
)
from .random import Generator, get_rng_state, seed, set_rng_state  # noqa: F401
