"""Device memory telemetry.

Reference: paddle/fluid/memory/stats.cc (peak/current allocation stats) →
paddle.device.cuda.max_memory_allocated etc. TPU-native: XLA owns the
allocator, so stats come from the PJRT device (`memory_stats()`); where
the runtime doesn't expose them (CPU backend, tunneled devices), usage is
computed from the live jax.Array set and the peak is maintained as the
max observed across queries (exact current usage, observed peak).
"""
from __future__ import annotations

from typing import Optional

import jax

_peak = {}
_reserved_peak = {}
_reset_floor = {}  # device -> PJRT peak_bytes_in_use at last reset


def _device(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    return device


def _live_bytes(dev) -> int:
    total = 0
    for a in jax.live_arrays():
        try:
            if dev in a.devices():
                total += a.nbytes // len(a.devices())
        except Exception:  # pragma: no cover — deleted arrays
            pass
    return total


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device
    (paddle.device.cuda.memory_allocated parity)."""
    dev = _device(device)
    stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
    cur = stats["bytes_in_use"] if stats else _live_bytes(dev)
    key = id(dev)
    _peak[key] = max(_peak.get(key, 0), cur)
    return int(cur)


def max_memory_allocated(device=None) -> int:
    dev = _device(device)
    stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
    if stats and "peak_bytes_in_use" in stats:
        dev_peak = int(stats["peak_bytes_in_use"])
        floor = _reset_floor.get(id(dev))
        if floor is None:
            return dev_peak
        # PJRT's peak is monotonic; after a reset, report the device peak
        # only once it exceeds the value at reset time, else the observed
        # current-usage peak since the reset
        if dev_peak > floor:
            return dev_peak
        memory_allocated(device)
        return int(_peak.get(id(dev), 0))
    memory_allocated(device)  # refresh observed peak
    return int(_peak.get(id(dev), 0))


def memory_reserved(device=None) -> int:
    dev = _device(device)
    stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
    if stats:
        return int(stats.get("bytes_reserved",
                             stats.get("bytes_in_use", 0)))
    return memory_allocated(device)


def max_memory_reserved(device=None) -> int:
    return max_memory_allocated(device)


def reset_peak_memory_stats(device=None):
    dev = _device(device)
    _peak.pop(id(dev), None)
    stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
    if stats and "peak_bytes_in_use" in stats:
        _reset_floor[id(dev)] = int(stats["peak_bytes_in_use"])


def empty_cache():
    """paddle.device.cuda.empty_cache parity — XLA frees buffers when the
    owning jax.Array dies; nothing to flush beyond a GC pass."""
    import gc
    gc.collect()
