"""Device management: paddle.device.set_device / get_device equivalents.

Reference: /root/reference/python/paddle/device/__init__.py (set_device /
get_device / is_compiled_with_*). Here devices are jax devices; the "current
device" determines where new tensors materialize (jax.default_device).
"""
from __future__ import annotations

import contextlib
import threading

import jax

from .place import CPUPlace, Place, TPUPlace, default_place

_state = threading.local()


def _current() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        p = default_place()
        _state.place = p
    return p


def set_device(device: str) -> Place:
    """Accepts 'cpu', 'tpu', 'tpu:0', and (compat) 'gpu'/'gpu:0' → tpu."""
    if isinstance(device, Place):
        _state.place = device
        return device
    dev = device.lower()
    idx = 0
    if ":" in dev:
        dev, idx_s = dev.split(":", 1)
        idx = int(idx_s)
    if dev == "cpu":
        place = CPUPlace()
    elif dev in ("tpu", "gpu", "cuda", "xpu", "npu"):
        place = TPUPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}; expected cpu/tpu[:i]")
    _state.place = place
    return place


def get_device() -> str:
    p = _current()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"tpu:{p.get_device_id()}"


def get_current_place() -> Place:
    return _current()


def current_jax_device():
    return _current().jax_device()


def device_count(device_type: str = "tpu") -> int:
    if device_type == "cpu":
        return len(jax.devices("cpu"))
    return len([d for d in jax.devices() if d.platform.lower() != "cpu"]) or 0


def is_compiled_with_cuda() -> bool:  # compat shim
    return False


def is_compiled_with_tpu() -> bool:
    return device_count("tpu") > 0


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


@contextlib.contextmanager
def device_guard(device: str):
    prev = _current()
    set_device(device)
    try:
        yield
    finally:
        _state.place = prev


def synchronize():
    """Block until all queued device work completes.

    XLA/jax dispatch is async; this is the analog of the reference's
    DeviceContext::Wait (/root/reference/paddle/phi/core/device_context.h).
    """
    try:
        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:  # pragma: no cover
        pass
