"""Named stat registry (reference: paddle/fluid/platform/monitor.cc —
STAT_ADD/STAT_RESET int64 counters exported for observability)."""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_reset(name: str = None):
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def stat_names():
    with _lock:
        return sorted(_stats)
