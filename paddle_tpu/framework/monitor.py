"""Named stat registry (reference: paddle/fluid/platform/monitor.cc —
STAT_ADD/STAT_RESET int64 counters exported for observability).

Backed by the unified telemetry layer: the stats ARE a label set on the
``paddle_monitor_stat_total`` Counter in ``observability.default_registry()``,
so everything recorded here shows up verbatim on a scraped ``/metrics``
page as ``paddle_monitor_stat_total{name="..."}``. The historical flat-int
API (stat_add/stat_get/stat_reset/stat_names) is unchanged;
``stats_snapshot()`` is the sanctioned bulk export — nothing outside
this module should reach into the underlying storage.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..observability.registry import default_registry

_counter = default_registry().counter(
    "paddle_monitor_stat_total",
    "framework STAT_ADD int64 counters (platform/monitor.cc analog)",
    ("name",))


def stat_add(name: str, value: int = 1) -> int:
    return int(_counter.labels(name=name).inc(int(value)))


def stat_get(name: str) -> int:
    child = _counter.get(name=name)
    return int(child.value) if child is not None else 0


def stat_reset(name: Optional[str] = None):
    if name is None:
        _counter.clear()
    else:
        _counter.remove(name=name)


def stat_names():
    return sorted(key[0] for key in _counter.label_values())


def stats_snapshot() -> Dict[str, int]:
    """All stats as one dict — the export the exposition layer (and any
    other consumer) uses instead of touching internal storage."""
    return {key[0]: int(child.value) for key, child in _counter.items()}
