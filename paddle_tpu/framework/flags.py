"""Typed global flag registry.

The reference centralizes ~90 gflags in /root/reference/paddle/phi/core/flags.cc
and exposes them through ``paddle.set_flags/get_flags`` with ``FLAGS_*`` env-var
overrides (/root/reference/python/paddle/fluid/framework.py:7764). This is the
TPU-native equivalent: a single typed registry, env-var override at definition
time, same Python surface.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Union


class _Flag:
    __slots__ = ("name", "value", "default", "type_", "help")

    def __init__(self, name, default, help_=""):
        self.name = name
        self.default = default
        self.type_ = type(default)
        self.help = help_
        env = os.environ.get(name)
        self.value = self._parse(env) if env is not None else default

    def _parse(self, s: str):
        if self.type_ is bool:
            return s.lower() in ("1", "true", "yes", "on")
        try:
            return self.type_(s)
        except (TypeError, ValueError) as e:
            # the bare int("two") ValueError names neither the flag nor
            # where the bad value came from — the env var IS the flag
            # name, so say all three
            raise ValueError(
                f"flag {self.name}: cannot parse {s!r} from environment "
                f"variable {self.name} as {self.type_.__name__} "
                f"(default: {self.default!r})") from e

    def set(self, v):
        if self.type_ is bool and isinstance(v, str):
            v = self._parse(v)
        try:
            self.value = self.type_(v)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"flag {self.name}: cannot coerce {v!r} to "
                f"{self.type_.__name__} (default: {self.default!r})"
            ) from e


_REGISTRY: Dict[str, _Flag] = {}

# Monotonic epoch bumped by every set_flags call. In-process memos
# derived from flag values (e.g. the per-signature AOT-executable memos
# at the compile-cache sites) key on this, so a flag flip or a
# repointed FLAGS_compile_cache_dir can never keep serving a stale
# memoized executable.
_GENERATION = 0


def flags_generation() -> int:
    return _GENERATION


def define_flag(name: str, default, help_: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    if name not in _REGISTRY:
        _REGISTRY[name] = _Flag(name, default, help_)
    return _REGISTRY[name]


def get_flags(flags: Union[str, List[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f if f.startswith("FLAGS_") else "FLAGS_" + f
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag: {f}")
        out[f] = _REGISTRY[key].value
    return out


def set_flags(flags: Dict[str, Any]):
    global _GENERATION
    for k, v in flags.items():
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag: {k}")
        _REGISTRY[key].set(v)
    _GENERATION += 1


def flag_value(name: str):
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key].value


def flag_ref(name: str) -> _Flag:
    """The live registry object for a flag. Hot paths bind this once
    and read ``.value`` directly — same liveness as ``flag_value``
    (``set_flags`` mutates the object in place) without paying a
    registry lookup per call."""
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key]


def flags_snapshot() -> Dict[str, Dict[str, Any]]:
    """Every registered flag with its live value, default, type name
    and help text — the bulk export pdlint's ``--dump-flags`` and
    debugging sessions use instead of reaching into ``_REGISTRY``."""
    return {name: {"value": f.value, "default": f.default,
                   "type": f.type_.__name__, "help": f.help}
            for name, f in sorted(_REGISTRY.items())}


# Core flags (the subset of the reference's flags.cc that has TPU meaning;
# others are accepted as inert toggles so reference scripts don't break).
define_flag("FLAGS_use_autotune", True, "kernel block-size autotuning (phi/kernels/autotune analog)")
define_flag("FLAGS_check_nan_inf", False, "check outputs for nan/inf after every op")
define_flag("FLAGS_benchmark", False, "synchronize after every op (for timing)")
define_flag("FLAGS_eager_op_jit_cache", True, "cache per-op compiled executables in eager mode")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "accepted for compat; XLA manages HBM")
define_flag("FLAGS_allocator_strategy", "auto_growth", "compat; XLA BFC allocator is used")
define_flag("FLAGS_cudnn_deterministic", False, "compat; maps to XLA deterministic ops")
define_flag("FLAGS_use_stream_safe_cuda_allocator", True, "compat no-op")
define_flag("FLAGS_new_executor_serial_run", False, "run static programs op-serially (debug)")
define_flag("FLAGS_enable_pir_api", False, "compat no-op")
define_flag("FLAGS_log_memory_stats", False, "log live/peak buffer stats on allocation")
define_flag("FLAGS_tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("FLAGS_selected_tpus", 0,
            "local TPU ordinal for this worker (the selected-gpus "
            "analog); the launcher exports it per rank, "
            "distributed.env reads it back as dev_id")
define_flag("FLAGS_flash_min_seqlen", 2048,
            "below this query length attention uses the XLA softmax path "
            "(faster end-to-end, PERF.md); the Pallas flash kernel kicks "
            "in at/above it where O(S^2) memory stops fitting")
define_flag("FLAGS_flash_block_q", 0,
            "flash-attention q block size override (0 = autotune/default); "
            "applies when the call is traced and no autotune cache entry "
            "exists for the shape")
define_flag("FLAGS_flash_block_k", 0,
            "flash-attention k block size override (0 = autotune/default)")

# Serving knobs (paddle_tpu.serving — the dynamic-batching layer).
define_flag("FLAGS_serving_max_batch_size", 8,
            "rows coalesced into one device batch before dispatch")
define_flag("FLAGS_serving_max_wait_ms", 2.0,
            "coalescing window: a batch dispatches when full or this "
            "many ms after its oldest request, whichever first")
define_flag("FLAGS_serving_queue_capacity", 64,
            "bounded request queue; submit raises QueueFullError beyond "
            "this (backpressure)")
define_flag("FLAGS_serving_default_timeout_ms", 0.0,
            "per-request deadline applied when submit() passes none "
            "(0 = no deadline); expired requests are dropped unrun")
define_flag("FLAGS_serving_pad_batch_pow2", True,
            "pad coalesced batches up to power-of-two row buckets so "
            "the XLA compile cache stays bounded under variable load")
define_flag("FLAGS_serving_capi_batching", False,
            "route PD_* C-ABI predictors through a shared "
            "InferenceServer so C hosts get request coalescing")
define_flag("FLAGS_serving_latency_window", 2048,
            "latency samples kept for the serving p50/p95/p99 metrics")
define_flag("FLAGS_serving_pipeline_depth", 2,
            "batches allowed in flight between dispatch and completion: "
            "the worker assembles batch N+1 while batch N computes on "
            "device (0 = synchronous execute, the pre-pipeline path)")
define_flag("FLAGS_serving_telemetry_port", -1,
            "HTTP telemetry endpoint (/metrics /healthz /statusz) the "
            "InferenceServer attaches on construction: -1 disabled, "
            "0 ephemeral port, >0 fixed port; one shared endpoint per "
            "process")
define_flag("FLAGS_serving_donate_inputs", True,
            "donate device input buffers to the jitted serving dispatch "
            "so XLA reuses them for outputs (effective on accelerator "
            "backends; CPU has no donation and falls back silently)")

# Decode serving knobs (paddle_tpu.serving.generation — the
# continuous-batching autoregressive decode engine).
define_flag("FLAGS_decode_max_batch", 8,
            "in-flight decode batch width: the decode step compiles "
            "ONCE at [max_batch, 1] and dead lanes are slot-masked, so "
            "this bounds both concurrency and the compiled shape")
define_flag("FLAGS_decode_page_size", 16,
            "tokens per KV-cache page; sequences hold pages of the "
            "preallocated per-layer pool via int32 block tables "
            "(PagedAttention layout), so cache memory scales with live "
            "tokens rather than max_seq_len x batch")
define_flag("FLAGS_decode_kv_pages", 0,
            "total pages per layer pool incl. the reserved trash page "
            "(0 = auto: enough for max_batch sequences at the model's "
            "max_seq_len)")
define_flag("FLAGS_decode_queue_capacity", 64,
            "bounded generation request queue; submit_generate raises "
            "QueueFullError beyond this (backpressure, matching submit)")
define_flag("FLAGS_decode_default_timeout_ms", 0.0,
            "scheduling deadline applied when submit_generate passes "
            "none (0 = no deadline); like serving submit, an expired "
            "request is dropped before prefill, never mid-stream")
define_flag("FLAGS_decode_prefix_cache", True,
            "shared-prefix KV reuse: keep finished sequences' FULL "
            "pages in a radix index keyed by token content, so a "
            "request whose prompt matches a cached prefix maps those "
            "pages into its block table (refcounted, copy-on-write at "
            "the divergence page) and prefills only its unique suffix; "
            "unreferenced cached pages are LRU-evicted under pool "
            "pressure")
define_flag("FLAGS_decode_spec_k", 0,
            "speculative decoding: tokens proposed per step by the "
            "draft model (GenerationServer(draft_model=...)); the "
            "target model verifies all k in one fixed-shape "
            "[max_batch, k+1] step with accept-and-resample, so "
            "output distribution matches non-speculative sampling "
            "(0 = off; ignored without a draft model)")
define_flag("FLAGS_decode_pallas_attention", False,
            "route the decode/chunked serving attention through the "
            "fused Pallas paged kernels (ops/pallas_paged_attention.py: "
            "K/V read through the block table inside the kernel, online "
            "softmax per page tile, no materialized gather) and serving "
            "prefill through the pallas_attention.mha flash path; off = "
            "the pure-JAX gather reference (always kept as fallback for "
            "unsupported shapes). Read once at GenerationServer "
            "construction — flipping it mid-process affects new servers "
            "only, never a compiled decoder")
define_flag("FLAGS_decode_kv_dtype", "",
            "KV pool storage dtype for serving: '' = model dtype, "
            "'float32', 'bfloat16', or 'int8' (symmetric absmax "
            "quantization with per-slot-per-head f32 scales stored "
            "alongside the pools; quantize-on-write, dequantize-on-read "
            "in both the Pallas tiles and the pure-JAX gather). int8 "
            "shrinks pool bytes ~3.5-4x, and auto pool sizing "
            "(FLAGS_decode_kv_pages=0) grants sub-f32 dtypes 2x pages "
            "= ~2x resident sequences per chip. Read once at server "
            "construction, like FLAGS_decode_pallas_attention")
define_flag("FLAGS_decode_warmup_from_manifest", False,
            "pre-compile a constructed GenerationServer's decode step "
            "and recorded prefill buckets from its persisted warmup "
            "manifest under FLAGS_compile_cache_dir")
define_flag("FLAGS_serving_mesh_mp", 1,
            "tensor-parallel degree of ONE serving replica: the "
            "replica spans a {'mp': N} device mesh, weights shard by "
            "the shard.py rule tables, paged KV pools shard along the "
            "heads axis ([pages, page_size, heads/mp, head_dim]), and "
            "the prefill/chunked/verify/decode entry points run GSPMD-"
            "partitioned across all N chips (serving/mesh.py). <=1 = "
            "single-shard (today's exact behavior: same fingerprints, "
            "no recompiles). num_heads must divide evenly or "
            "construction fails fast. Read once at server/backend "
            "construction, like FLAGS_decode_pallas_attention")

# Persistent compile cache (paddle_tpu.compile_cache — cold-start
# amortization across processes).
define_flag("FLAGS_compile_cache_dir", "",
            "directory for the persistent AOT compile cache (serialized "
            "executables keyed by function/shape/mesh/flag/version "
            "fingerprints); empty = disabled. A warm cache lets a "
            "restarted process skip trace+XLA-compile at every wired "
            "compile site (jit, TrainStep, serving warmup/dispatch). "
            "TRUSTED PATH ONLY: entries are unpickled on load, so a "
            "writer to this directory can execute code in every reader "
            "— it is created 0o700 and must never be shared or "
            "group-writable")
define_flag("FLAGS_compile_cache_max_bytes", 1 << 30,
            "size bound for FLAGS_compile_cache_dir: least-recently-"
            "used entries are evicted past this many bytes (0 = "
            "unbounded)")
define_flag("FLAGS_serving_warmup_from_manifest", False,
            "pre-warm a constructed InferenceServer from its persisted "
            "warmup manifest (the batch signatures a previous process "
            "actually compiled) when one exists under "
            "FLAGS_compile_cache_dir — the restart-storm fast path")

# Observability knobs (paddle_tpu.observability — the telemetry layer).
define_flag("FLAGS_training_telemetry", False,
            "auto-inject the TrainingTelemetryCallback into Model.fit "
            "(step time, examples/sec, loss into the metric registry)")
define_flag("FLAGS_profiler_span_metrics", False,
            "mirror profiler RecordEvent span durations into the "
            "paddle_profiler_span_ms histogram so chrome traces and "
            "scraped /metrics agree")

# Goodput ledger + continuous step profiler + SLO monitor
# (paddle_tpu.observability.{goodput,stepprof,slo}).
define_flag("FLAGS_goodput_tolerance", 0.02,
            "goodput_report() accounting tolerance: the report's "
            "'closes' bit requires categories (incl. derived idle) to "
            "sum to elapsed wall-clock within this fraction")
define_flag("FLAGS_stepprof_window", 512,
            "bound of the continuous step profiler's per-step "
            "envelope ring (oldest envelopes are evicted past this)")
define_flag("FLAGS_stepprof_anomaly_k", 6.0,
            "straggler threshold: a step slower than "
            "ewma + k * 1.4826 * MAD of its kind is flagged and "
            "promoted into the trace flight recorder as an error span")
define_flag("FLAGS_stepprof_min_samples", 32,
            "step samples per kind before the straggler detector "
            "arms (the EWMA/MAD baseline warm-up)")
define_flag("FLAGS_slo_eval_interval_s", 10.0,
            "cadence of the background SLO evaluator thread "
            "(SLOMonitor.start(); explicit evaluate() calls are "
            "always allowed)")

# Executable cost & roofline observability + on-demand device
# profiling (paddle_tpu.observability.xstats — the /execz registry
# and the /profilez capture ring).
define_flag("FLAGS_xstats_enable", True,
            "populate the process-wide executable registry at every "
            "compile site (XLA cost/memory analysis per signature, "
            "the /execz page, and the paddle_mfu{kind=} join with the "
            "continuous step profiler); off = every hook is a no-op")
define_flag("FLAGS_xstats_max_entries", 512,
            "bound of the executable registry: least-recently-"
            "registered entries are evicted past this many (counted "
            "in paddle_exec_evicted_total)")
define_flag("FLAGS_device_peak_flops", 0.0,
            "per-chip peak FLOP/s for MFU and roofline computation; "
            "0 = use the built-in per-platform table (TPU v5e bf16 "
            "default). CPU CI sets this explicitly — no table entry "
            "pretends to know a host CPU's peak")
define_flag("FLAGS_device_peak_bytes_per_s", 0.0,
            "per-chip peak memory bandwidth (bytes/s) for the "
            "bandwidth-utilization gauge and the roofline ridge "
            "point; 0 = per-platform table, same contract as "
            "FLAGS_device_peak_flops")
define_flag("FLAGS_profile_dir", "",
            "directory of the bounded on-disk profile-capture ring "
            "(/profilez artifacts); empty = a per-process directory "
            "under the system temp dir")
define_flag("FLAGS_profile_ring", 8,
            "max retained capture artifacts in the /profilez ring "
            "(oldest artifact files are deleted past this)")
define_flag("FLAGS_profile_max_ms", 2000.0,
            "hard bound on one /profilez capture duration — a "
            "larger duration_ms query parameter is clamped here so a "
            "scrape can never stall a replica for long")
define_flag("FLAGS_profile_min_interval_s", 30.0,
            "rate limit between profile captures (manual or "
            "anomaly-triggered); captures inside the interval are "
            "refused and counted in paddle_profile_rate_limited_total")
define_flag("FLAGS_profile_on_anomaly", False,
            "arm anomaly-triggered capture: a stepprof straggler "
            "kicks off one rate-limited background device-profile "
            "capture whose artifact records the straggler span's "
            "trace id (see FLAGS_profile_anomaly_ms)")
define_flag("FLAGS_profile_anomaly_ms", 500.0,
            "duration of an anomaly-triggered capture (bounded by "
            "FLAGS_profile_max_ms like every capture)")

# Distributed request tracing (paddle_tpu.observability.tracing —
# router->worker->engine spans + the /tracez flight recorder).
define_flag("FLAGS_trace_sample_rate", 0.0,
            "head-sampling rate for distributed request traces "
            "(0 = tracing off, 1 = every request). The decision is "
            "made once at ingress, deterministically from the trace "
            "id, and propagated in the traceparent header; errored/"
            "shed/deadline requests are tail-promoted into the "
            "recorder regardless of the coin flip")
define_flag("FLAGS_trace_buffer_spans", 4096,
            "bound of the in-process span flight recorder (/tracez): "
            "oldest spans are evicted past this many")
define_flag("FLAGS_trace_max_spans_per_trace", 256,
            "per-trace span cap in the flight recorder AND on the "
            "unsampled pending list, so one long decode stream "
            "cannot evict every other trace (excess spans are "
            "counted as dropped)")

# Numerics & silent-data-corruption observability
# (paddle_tpu.observability.numerics — NaN/Inf tripwires, sampled
# shadow-verification against the pure-JAX oracle, device canary
# sweeps, and the /numericsz surface). FLAGS_check_nan_inf (defined
# with the core flags above) arms the tripwires at 100% duty; these
# knobs give the fleet a cheaper sampled regime.
define_flag("FLAGS_numerics_sample_rate", 0.0,
            "fraction of train/decode steps whose output health stats "
            "(finite fraction, max-abs, argmax-entropy, grad norm) are "
            "published; FLAGS_check_nan_inf=true overrides this to "
            "every step. The device reductions are fixed-shape and "
            "their host read is deferred one step, so sampling costs "
            "no extra device sync")
define_flag("FLAGS_numerics_shadow_rate", 0.0,
            "duty cycle of decode/chunked/verify shadow-verification: "
            "a sampled dispatch is re-executed through the pure-JAX "
            "oracle (use_pallas=False, non-donating) and max-abs "
            "logit divergence is published as "
            "paddle_numerics_shadow_divergence{kind,dtype}")
define_flag("FLAGS_numerics_canary_period_s", 0.0,
            "period of the per-worker deterministic checksum canary "
            "sweep (SDC detection); 0 disables. The sweep also runs "
            "on not-ready -> ready transitions; a failing canary "
            "quarantines the replica (readiness flip + breaker open)")

# Serving-fleet knobs (paddle_tpu.serving.fleet — router + N replica
# worker processes with rolling hot weight swap).
define_flag("FLAGS_serving_ready_requires_warmup", False,
            "gate readiness (/readyz, InferenceServer.ready, "
            "GenerationServer.ready) on warmup: the server reports "
            "not-ready until warmup()/warmup_from_manifest() completes. "
            "Fleet workers enable this so the router never routes "
            "traffic to a replica that would compile on the request "
            "path; liveness (/healthz) is unaffected")
define_flag("FLAGS_fleet_replicas", 2,
            "default replica count a ReplicaSupervisor spawns when the "
            "caller does not pass one")
define_flag("FLAGS_fleet_retries", 2,
            "router retry budget per batch: a dispatch shed with "
            "QueueFullError (HTTP 429) or refused by a not-ready "
            "replica is retried on another replica this many times "
            "before the batch fails with QueueFullError")
define_flag("FLAGS_fleet_health_interval_ms", 200.0,
            "router readiness-poll cadence: every interval each known "
            "replica's /readyz is probed and the routable set updated")
define_flag("FLAGS_fleet_restart_backoff_ms", 200.0,
            "supervisor respawn backoff after a replica process exits "
            "unexpectedly (doubles per consecutive crash of the same "
            "replica, capped at 30x)")
define_flag("FLAGS_fleet_request_timeout_s", 120.0,
            "router-side HTTP timeout for one forwarded batch or "
            "generation stream read; a replica that blows it fails "
            "only the in-flight requests riding that connection")
define_flag("FLAGS_fleet_drain_timeout_s", 30.0,
            "rolling-swap drain bound: max seconds swap_weights waits "
            "for one draining replica's outstanding requests to reach "
            "zero before the swap aborts (remaining replicas keep the "
            "old weights — never a half-broken fleet)")

# Fleet resilience knobs (paddle_tpu.serving.fleet.resilience —
# deadline propagation, per-replica circuit breakers, hedged
# requests, retry backoff, and the device-wedge watchdog).
define_flag("FLAGS_fleet_retry_backoff_ms", 10.0,
            "base of the router's exponential retry backoff: retry N "
            "sleeps uniform[0, min(cap, base * 2^N)] (full jitter, so "
            "a fleet-wide brownout does not trigger a synchronized "
            "retry storm); 0 = immediate retries (the pre-resilience "
            "behavior)")
define_flag("FLAGS_fleet_retry_backoff_max_ms", 500.0,
            "cap of the router's exponential retry backoff sleep")
define_flag("FLAGS_fleet_breaker_window", 16,
            "per-replica circuit-breaker rolling outcome window: the "
            "last N dispatch outcomes drive the open/close decision")
define_flag("FLAGS_fleet_breaker_failure_ratio", 0.5,
            "circuit-breaker open threshold: the breaker opens when "
            "failures / window samples reaches this ratio (with at "
            "least FLAGS_fleet_breaker_min_samples outcomes seen)")
define_flag("FLAGS_fleet_breaker_min_samples", 4,
            "minimum outcomes in the rolling window before the "
            "failure ratio can open a breaker (no opening on the "
            "first blip)")
define_flag("FLAGS_fleet_breaker_open_ms", 1000.0,
            "circuit-breaker cooldown: an open breaker sheds all "
            "traffic from its replica for this long, then moves to "
            "half-open and admits ONE probe request; the probe's "
            "outcome closes or re-opens it")
define_flag("FLAGS_fleet_breaker_latency_ms", 0.0,
            "slow-but-alive threshold: a SUCCESSFUL dispatch slower "
            "than this counts as a breaker failure, so a replica "
            "serving 100x latency while /readyz-green still gets "
            "drained (0 = latency never trips the breaker)")
define_flag("FLAGS_fleet_hedge_ms", 0.0,
            "request hedging floor: when a submit/submit_many "
            "dispatch is still pending after max(this, the replica "
            "latency window's FLAGS_fleet_hedge_quantile), a hedge "
            "fires to a SECOND replica and the first response wins "
            "(idempotent batch path only — submit_generate never "
            "hedges); 0 = hedging off")
define_flag("FLAGS_fleet_hedge_quantile", 0.95,
            "latency quantile of the primary replica's rolling window "
            "used as the adaptive hedge trigger (bounded below by "
            "FLAGS_fleet_hedge_ms)")
define_flag("FLAGS_fleet_wedge_timeout_ms", 0.0,
            "device-wedge watchdog: a worker dispatch in flight "
            "longer than this flips /readyz to not-ready, fails "
            "waiting requests with ReplicaWedgedError and asks the "
            "supervisor for a restart (worker processes exit; the "
            "respawn is a warm start). 0 = watchdog off")

# ---- multi-tenant scheduling (serving/scheduling/) ----
define_flag("FLAGS_sched_policy_file", "",
            "JSON tenant-policy file (rate/burst/weight/priority per "
            "tenant); hot-reloaded on mtime change, like /reload. "
            "Empty = flags-only policy")
define_flag("FLAGS_sched_default_rate", 0.0,
            "default tenant token-bucket refill rate in tokens/s "
            "(admission cost is 1 token per request at the worker, "
            "prompt+max_new tokens at the generation engine); "
            "0 = unlimited")
define_flag("FLAGS_sched_default_burst", 64.0,
            "default tenant token-bucket depth (burst allowance)")
define_flag("FLAGS_sched_default_weight", 1.0,
            "default tenant weighted-fair-queuing weight (a weight-4 "
            "tenant drains 4x the token volume of a weight-1 tenant "
            "under contention)")
define_flag("FLAGS_sched_default_priority", "standard",
            "default tenant priority class: realtime | standard | "
            "batch (admission prefers realtime; page-pressure "
            "preemption evicts batch first and never touches a "
            "higher class)")

# ---- SLO-driven autoscaling (serving/scheduling/autoscaler.py) ----
define_flag("FLAGS_autoscale_min_replicas", 1,
            "autoscaler floor: never scale the fleet below this")
define_flag("FLAGS_autoscale_max_replicas", 8,
            "autoscaler ceiling: never scale the fleet above this")
define_flag("FLAGS_autoscale_cooldown_s", 30.0,
            "minimum seconds between scale actions in either "
            "direction (hysteresis against flapping)")
define_flag("FLAGS_autoscale_scale_in_quiet_s", 120.0,
            "scale IN only after this long with no burn-rate rule "
            "firing and queue/occupancy low (asymmetric hysteresis: "
            "out fast, in slow)")
define_flag("FLAGS_autoscale_queue_high", 16.0,
            "router/worker queue depth above which the autoscaler "
            "scales out")
define_flag("FLAGS_autoscale_occupancy_high", 0.85,
            "decode-slot occupancy fraction above which the "
            "autoscaler scales out")
define_flag("FLAGS_autoscale_interval_s", 5.0,
            "autoscaler control-loop evaluation period in seconds")

# ---- runtime lockdep sanitizer (analysis/sanitizer.py) ----
define_flag("FLAGS_lockdep", False,
            "instrument threading.Lock/RLock/Condition constructed by "
            "repo code with the lockdep sanitizer: per-thread "
            "acquisition stacks, an observed lock-order graph, and an "
            "error the FIRST time an AB/BA order inversion is "
            "observed (not only when it deadlocks). Installed by the "
            "tier-1 pytest fixture when set; opt-in because every "
            "guarded acquire pays a bookkeeping tax")
define_flag("FLAGS_lockdep_hold_warn_ms", 100.0,
            "lockdep flags any instrumented lock held longer than "
            "this many milliseconds (a long hold under traffic is a "
            "convoy; holding across I/O is the static LD002 rule's "
            "runtime twin). 0 disables hold-time tracking")
define_flag("FLAGS_lockdep_raise", True,
            "raise LockdepViolation in the acquiring thread on the "
            "first observed inversion per lock pair (False = record "
            "in sanitizer.report() only — crash-averse production "
            "canaries)")
