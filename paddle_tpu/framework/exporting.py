"""Serializable compiled-program artifact (the fast serving path).

Reference: paddle's inference artifact is a ProgramDesc protobuf + packed
params (/root/reference/python/paddle/static/io.py:442,723 and
paddle/fluid/jit/serializer.cc). TPU-native design: the traced program is
serialized as StableHLO bytes via ``jax.export`` (portable across processes
and compiled AOT by XLA at load), weights ride inside it. Artifacts are
exported for both cpu and tpu platforms so a model saved on a TPU host can
be smoke-tested on CPU and vice versa.

The REFERENCE wire format (.pdmodel ProgramDesc protobuf + .pdiparams
tensor stream) is written separately by static/pdmodel_export.py; this
module's artifact is the whole-program-compiled twin, stored as ONE pickle
file ``<prefix>.pdexec`` {format, stablehlo bytes, weight_names, weights,
feed specs (name/shape/dtype), nr outputs}.
"""
from __future__ import annotations

import os
import pickle
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

FORMAT = "paddle_tpu.export.v2"  # v2: single .pdexec file, weights embedded


def _spec_of(a) -> dict:
    shape = []
    for d in a.shape:
        try:
            shape.append(int(d))
        except Exception:  # symbolic dim (shape polymorphism) -> dynamic
            shape.append(None)
    return {"shape": shape, "dtype": str(np.dtype(a.dtype))}


def export_artifact(path_prefix: str, fn: Callable,
                    weights: Dict[str, np.ndarray],
                    input_specs: Sequence[jax.ShapeDtypeStruct],
                    feed_names: Optional[List[str]] = None) -> str:
    """Serialize ``fn(weight_list, *inputs)`` + weights under path_prefix.

    ``fn`` takes the weight arrays as a list ordered by sorted weight name,
    then the feed arrays; returns any pytree of arrays.
    """
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    names = sorted(weights)
    w_specs = [jax.ShapeDtypeStruct(np.shape(weights[n]),
                                    np.asarray(weights[n]).dtype)
               for n in names]
    try:
        exp = jax.export.export(jax.jit(fn), platforms=("cpu", "tpu"))(
            w_specs, *input_specs)
    except Exception:
        # some programs only lower for the current backend (e.g. pallas
        # kernels have no cpu lowering outside interpret mode)
        exp = jax.export.export(jax.jit(fn))(w_specs, *input_specs)
    meta = {
        "format": FORMAT,
        "stablehlo": exp.serialize(),
        "weight_names": names,
        "weights": {n: np.asarray(weights[n]) for n in names},
        "feed_names": feed_names or [f"feed_{i}"
                                     for i in range(len(input_specs))],
        "feeds": [_spec_of(s) for s in input_specs],
        "n_outputs": len(exp.out_avals),
        "platforms": list(exp.platforms),
    }
    with open(path_prefix + ".pdexec", "wb") as f:
        pickle.dump(meta, f)
    return path_prefix


class LoadedArtifact:
    """Deserialized program + weights; callable on feed arrays."""

    def __init__(self, path_prefix: str,
                 params_path: Optional[str] = None):
        with open(path_prefix + ".pdexec", "rb") as f:
            meta = pickle.load(f)
        if meta.get("format") != FORMAT:
            raise ValueError(
                f"{path_prefix}.pdexec is not a {FORMAT} artifact")
        self.weights = meta["weights"]
        if params_path is not None:
            # explicit weight override: a pickle dict, or a reference
            # save_combine tensor stream (same sorted-name order as
            # weight_names)
            with open(params_path, "rb") as f:
                raw = f.read()
            if raw[:1] == b"\x80":
                self.weights = pickle.loads(raw)
            else:
                from ..static.pdmodel import parse_combined_params
                try:
                    parsed = parse_combined_params(
                        raw, meta["weight_names"])
                except ValueError as e:
                    raise ValueError(
                        f"{params_path} does not match this artifact's "
                        f"weight list (a co-exported .pdiparams may carry "
                        f"extra folded constants — serve via the "
                        f".pdmodel/.pdiparams pair instead): {e}") from e
                for n, arr in parsed.items():
                    want = np.shape(meta["weights"][n])
                    if tuple(arr.shape) != tuple(want):
                        raise ValueError(
                            f"{params_path}: tensor {n!r} has shape "
                            f"{arr.shape}, artifact expects {want}")
                self.weights = parsed
        self.meta = meta
        self.feed_names = meta["feed_names"]
        self.feeds = meta["feeds"]
        # attribute-style jax.export only resolves after the submodule
        # was imported somewhere; a fresh serving-only process (jit.load
        # / Predictor with no prior jit.save) must import it explicitly
        from jax import export as jexport
        self._exported = jexport.deserialize(meta["stablehlo"])
        self._commit_weights()

    def _commit_weights(self):
        # device-resident once; otherwise every __call__ would re-transfer
        # all weights host-to-device (serving hot path)
        import jax.numpy as jnp
        self._weight_list = [jnp.asarray(self.weights[n])
                             for n in self.meta["weight_names"]]

    def __call__(self, *inputs):
        return self._exported.call(self._weight_list, *inputs)

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.weights = dict(weights)
        self._commit_weights()


def load_artifact(path_prefix: str,
                  params_path: Optional[str] = None) -> LoadedArtifact:
    return LoadedArtifact(path_prefix, params_path)
