"""Serializable program artifact — the pdmodel/pdiparams equivalent.

Reference: paddle's inference artifact is a ProgramDesc protobuf + packed
params (/root/reference/python/paddle/static/io.py:442,723 and
paddle/fluid/jit/serializer.cc). TPU-native design: the traced program is
serialized as StableHLO bytes via ``jax.export`` (portable across processes
and compiled AOT by XLA at load), weights ride next to it. Artifacts are
exported for both cpu and tpu platforms so a model saved on a TPU host can
be smoke-tested on CPU and vice versa.

Artifact layout (``<prefix>.pdmodel`` + ``<prefix>.pdiparams``):
- pdmodel:  pickled dict {format, stablehlo bytes, weight_names,
            feed specs (name/shape/dtype), nr outputs}
- pdiparams: pickled dict name -> np.ndarray
"""
from __future__ import annotations

import os
import pickle
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

FORMAT = "paddle_tpu.export.v1"


def _spec_of(a) -> dict:
    shape = []
    for d in a.shape:
        try:
            shape.append(int(d))
        except Exception:  # symbolic dim (shape polymorphism) -> dynamic
            shape.append(None)
    return {"shape": shape, "dtype": str(np.dtype(a.dtype))}


def export_artifact(path_prefix: str, fn: Callable,
                    weights: Dict[str, np.ndarray],
                    input_specs: Sequence[jax.ShapeDtypeStruct],
                    feed_names: Optional[List[str]] = None) -> str:
    """Serialize ``fn(weight_list, *inputs)`` + weights under path_prefix.

    ``fn`` takes the weight arrays as a list ordered by sorted weight name,
    then the feed arrays; returns any pytree of arrays.
    """
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    names = sorted(weights)
    w_specs = [jax.ShapeDtypeStruct(np.shape(weights[n]),
                                    np.asarray(weights[n]).dtype)
               for n in names]
    try:
        exp = jax.export.export(jax.jit(fn), platforms=("cpu", "tpu"))(
            w_specs, *input_specs)
    except Exception:
        # some programs only lower for the current backend (e.g. pallas
        # kernels have no cpu lowering outside interpret mode)
        exp = jax.export.export(jax.jit(fn))(w_specs, *input_specs)
    meta = {
        "format": FORMAT,
        "stablehlo": exp.serialize(),
        "weight_names": names,
        "feed_names": feed_names or [f"feed_{i}"
                                     for i in range(len(input_specs))],
        "feeds": [_spec_of(s) for s in input_specs],
        "n_outputs": len(exp.out_avals),
        "platforms": list(exp.platforms),
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({n: np.asarray(weights[n]) for n in names}, f)
    return path_prefix


class LoadedArtifact:
    """Deserialized program + weights; callable on feed arrays."""

    def __init__(self, path_prefix: str,
                 params_path: Optional[str] = None):
        with open(path_prefix + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        if meta.get("format") != FORMAT:
            raise ValueError(
                f"{path_prefix}.pdmodel is not a {FORMAT} artifact")
        with open(params_path or path_prefix + ".pdiparams", "rb") as f:
            self.weights = pickle.load(f)
        self.meta = meta
        self.feed_names = meta["feed_names"]
        self.feeds = meta["feeds"]
        self._exported = jax.export.deserialize(meta["stablehlo"])
        self._commit_weights()

    def _commit_weights(self):
        # device-resident once; otherwise every __call__ would re-transfer
        # all weights host-to-device (serving hot path)
        import jax.numpy as jnp
        self._weight_list = [jnp.asarray(self.weights[n])
                             for n in self.meta["weight_names"]]

    def __call__(self, *inputs):
        return self._exported.call(self._weight_list, *inputs)

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.weights = dict(weights)
        self._commit_weights()


def load_artifact(path_prefix: str,
                  params_path: Optional[str] = None) -> LoadedArtifact:
    return LoadedArtifact(path_prefix, params_path)
