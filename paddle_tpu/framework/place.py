"""Places — logical device locations.

Mirrors the reference's ``phi::Place`` hierarchy
(/root/reference/paddle/phi/common/place.h:58) with the device set that makes
sense on a TPU-native stack: CPUPlace and TPUPlace (CUDAPlace is accepted as an
alias for TPUPlace so reference scripts keep running, with a warning).
"""
from __future__ import annotations

import jax


class Place:
    """Base place. A place maps onto a jax.Device."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.device_type, self._device_id))

    def jax_device(self):
        """Resolve to a concrete jax.Device — PROCESS-LOCAL ones only: in
        multi-controller SPMD jax.devices() lists every process's devices,
        and host data committed to another process's device cannot feed
        compiled steps (cross-host reshard is unsupported)."""
        devs = [d for d in jax.local_devices()
                if _matches(d, self.device_type)]
        if not devs:
            # Fall back to PROCESS-LOCAL host CPU devices (always present;
            # the global jax.devices("cpu") list would hand other
            # processes' devices back on rank > 0)
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:  # pragma: no cover — no cpu backend
                devs = jax.devices("cpu")
        return devs[self._device_id % len(devs)]


def _matches(dev, device_type: str) -> bool:
    plat = dev.platform.lower()
    if device_type == "cpu":
        return plat == "cpu"
    if device_type == "tpu":
        # axon/tpu platforms both present as accelerators
        return plat not in ("cpu",)
    return False


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    device_type = "tpu"

    def __repr__(self):
        return f"Place(tpu:{self._device_id})"


class CUDAPlace(TPUPlace):
    """Compat alias: reference scripts constructing CUDAPlace land on TPU."""

    def __repr__(self):
        return f"Place(tpu:{self._device_id})  # CUDAPlace compat"


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


class IPUPlace(TPUPlace):
    """Compat alias: lands on TPU like CUDAPlace/XPUPlace."""


class MLUPlace(TPUPlace):
    """Compat alias: lands on TPU like CUDAPlace/XPUPlace."""


class NPUPlace(TPUPlace):
    pass


def _accelerator_available() -> bool:
    try:
        return any(d.platform.lower() != "cpu" for d in jax.devices())
    except RuntimeError:  # pragma: no cover
        return False


def default_place() -> Place:
    return TPUPlace(0) if _accelerator_available() else CPUPlace()
