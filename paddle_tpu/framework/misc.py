"""Top-level odds and ends: iinfo/finfo, LazyGuard, rng-state shims,
printoptions, reader batch, flops counter.

Reference spots: python/paddle/framework/__init__.py (iinfo/finfo over
paddle dtypes), python/paddle/fluid/lazy_init.py (LazyGuard),
python/paddle/batch.py (batch reader decorator), python/paddle/hapi/
dynamic_flops.py:28 (flops).
"""
from __future__ import annotations

import numpy as np

from . import dtype as dtype_mod
from .random import get_rng_state, set_rng_state

__all__ = [
    "iinfo", "finfo", "LazyGuard", "get_cuda_rng_state",
    "set_cuda_rng_state", "set_printoptions", "disable_signal_handler",
    "batch", "flops", "set_grad_enabled", "check_shape",
]


class _DTypeInfo:
    def __init__(self, info):
        self._info = info
        for k in ("min", "max", "bits", "dtype"):
            if hasattr(info, k):
                setattr(self, k, getattr(info, k))
        if hasattr(info, "eps"):
            self.eps = float(info.eps)
            self.tiny = float(info.tiny)
            self.smallest_normal = float(info.tiny)
            self.resolution = float(info.resolution)

    def __repr__(self):
        return repr(self._info)


def iinfo(dtype):
    return _DTypeInfo(np.iinfo(dtype_mod.to_jax_dtype(dtype)))


def finfo(dtype):
    import jax.numpy as jnp
    return _DTypeInfo(jnp.finfo(dtype_mod.to_jax_dtype(dtype)))


class LazyGuard:
    """Context manager for deferred parameter initialization.

    The reference (fluid/lazy_init.py) skips initializer kernels inside
    the guard and materializes later. Here, layers built inside the guard
    get ABSTRACT parameters (``jax.ShapeDtypeStruct`` — shape/dtype, no
    buffer): the model can be traced, sharded and AOT-compiled (e.g. the
    ERNIE-10B memory plan in ``__graft_entry__``) without materializing
    tens of GB. Materialize with ``layer.to_static``-style export or by
    re-building the layer outside the guard and loading a checkpoint."""

    _active = False

    def __enter__(self):
        LazyGuard._active = True
        return self

    def __exit__(self, *exc):
        LazyGuard._active = False
        return False


def get_cuda_rng_state():
    """CUDA-name compat: returns the framework RNG state (the TPU build
    has one unified key chain)."""
    return [get_rng_state()]


def set_cuda_rng_state(state_list):
    if state_list:
        set_rng_state(state_list[0])


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr printing options (maps onto numpy's printoptions,
    which Tensor.__repr__ uses)."""
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    np.set_printoptions(**kwargs)


def disable_signal_handler():
    """No-op: the reference unhooks its C++ signal handlers; this build
    installs none."""


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference: python/paddle/batch.py):
    generator of samples -> generator of sample-lists."""
    if not isinstance(batch_size, (int, np.integer)) or batch_size <= 0:
        raise ValueError("batch_size should be a positive integer value, "
                         f"but got {batch_size!r}")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def set_grad_enabled(mode):
    """Context manager / switch for autograd recording (reference:
    autograd mode guard)."""
    from ..core import autograd as ag

    class _Guard:
        def __init__(self, m):
            self._prev = ag.grad_enabled()
            ag._set_grad_enabled(bool(m))

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            ag._set_grad_enabled(self._prev)
            return False

    return _Guard(mode)


def check_shape(shape):
    """Validate a shape argument (reference utils/layers_utils.py:469)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if s is not None and not isinstance(s, (int, np.integer)) \
                    and s != -1:
                raise TypeError(f"invalid shape element {s!r}")
    return shape


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Estimate forward FLOPs of a model (reference hapi/dynamic_flops.py).

    Counts multiply-adds as 2 FLOPs for Linear/Conv2D and matmul-free
    costs for norm/activation layers, via forward hooks on a dry run.
    """
    import paddle_tpu as P
    from ..nn import Conv2D, Linear

    totals = {"flops": 0}
    rows = []
    hooks = []

    def conv_hook(layer, inputs, output):
        x = inputs[0]
        kh, kw = layer.kernel_size
        cout = output.shape[1]
        hw = int(np.prod(output.shape[2:]))
        cin_g = layer.weight.shape[1]
        fl = 2 * cout * hw * cin_g * kh * kw * x.shape[0]
        totals["flops"] += fl
        rows.append((type(layer).__name__, fl))

    def linear_hook(layer, inputs, output):
        x = inputs[0]
        n = int(np.prod(x.shape[:-1]))
        fl = 2 * n * layer.weight.shape[0] * layer.weight.shape[1]
        totals["flops"] += fl
        rows.append((type(layer).__name__, fl))

    custom_ops = custom_ops or {}
    for m in net.sublayers():
        if type(m) in custom_ops:
            hooks.append(m.register_forward_post_hook(custom_ops[type(m)]))
        elif isinstance(m, Conv2D):
            hooks.append(m.register_forward_post_hook(conv_hook))
        elif isinstance(m, Linear):
            hooks.append(m.register_forward_post_hook(linear_hook))

    was_training = net.training
    net.eval()
    try:
        x = P.to_tensor(np.zeros(input_size, dtype=np.float32))
        net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        for name, fl in rows:
            print(f"{name:>16}: {fl:,}")
    return totals["flops"]
