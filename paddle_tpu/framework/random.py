"""Global RNG state.

The reference keeps per-device ``phi::Generator`` states
(/root/reference/paddle/phi/core/generator.h) seeded by ``paddle.seed``. JAX
randomness is functional (explicit keys), so the framework keeps a stateful
Generator that hands out fresh subkeys to each consuming op — stateful API on
the outside, pure keys on the inside. Traced/jit code should use
``paddle_tpu.nn.functional`` ops that accept explicit seeds, or rely on the
per-call key threading the jit wrapper does.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful splitter over a jax PRNG key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = jax.random.key(int(seed))
            self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Return a fresh subkey; advances state."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            self._counter += 1
            return sub

    def get_state(self):
        with self._lock:
            return (self._seed, self._counter, jax.random.key_data(self._key))

    def set_state(self, state):
        seed, counter, key_data = state
        with self._lock:
            self._seed = seed
            self._counter = counter
            self._key = jax.random.wrap_key_data(np.asarray(key_data))


# LAZY: jax.random.key touches the device backend, and `import paddle_tpu`
# must not (launcher/tooling processes import the package without ever
# running an op; an unreachable accelerator would hang them at import)
_default_generator = None


def _default():
    global _default_generator
    if _default_generator is None:
        _default_generator = Generator(
            seed=np.random.randint(0, 2 ** 31 - 1))
    return _default_generator

# When tracing a whole training step (paddle_tpu.jit.TrainStep), random ops
# must derive keys from a per-call traced base key instead of host state, so
# each compiled step invocation gets fresh randomness. This scope provides
# that base; next_key() folds an incrementing counter into it.
_trace_scope = threading.local()


class traced_key_scope:
    def __init__(self, base_key):
        self.base_key = base_key

    def __enter__(self):
        self._prev = getattr(_trace_scope, "state", None)
        _trace_scope.state = {"base": self.base_key, "counter": 0}
        return self

    def __exit__(self, *exc):
        _trace_scope.state = self._prev
        return False


def seed(s: int) -> Generator:
    """paddle.seed equivalent: reseed the global generator."""
    return _default().manual_seed(s)


def default_generator() -> Generator:
    return _default()


def next_key():
    st = getattr(_trace_scope, "state", None)
    if st is not None:
        st["counter"] += 1
        return jax.random.fold_in(st["base"], st["counter"])
    return _default().next_key()


def get_rng_state():
    return [_default().get_state()]


def set_rng_state(states):
    _default().set_state(states[0])
