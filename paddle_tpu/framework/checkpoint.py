"""Crash-safe sharded + async checkpointing keyed by PartitionSpec.

Reference: fleet sharded-model save utils
(/root/reference/python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_utils.py) and auto-parallel distributed save with
merge-on-load (auto_parallel/dist_saver.py); SURVEY §5.4 prescribes a
tensorstore-style sharded checkpoint for the TPU build. This module is
the durable layer under ``paddle_tpu.elastic.CheckpointManager``.

Format (directory)::

  meta.json    {"format": 2, "entries": {name: {shape, dtype, spec,
                file, sha256[, stored_as]}}}   — written LAST
  extra.json   optional JSON sidecar (training state, RNG scalars)
  <file>.npy   one host-gathered FULL array per entry

Crash-safety protocol (the part ``elastic`` depends on):

- **host snapshot before return**: every array is copied device→host
  (``np.asarray``) *before* ``save_sharded`` returns, so a donated or
  in-place-updated device buffer (``TrainStep`` donation) can never
  leak post-save values into the checkpoint;
- **staged atomic commit**: all files are written into a
  ``<path>.tmp-<token>`` staging directory, each fsync'd, ``meta.json``
  written last, the directory fsync'd, then ``os.replace``d onto the
  final path (a directory rename — atomic on POSIX). A ``kill -9`` at
  ANY instant leaves either the previous checkpoint or the new one
  fully intact; a torn staging dir is ignored by every reader and swept
  by the manager on startup;
- **integrity manifest**: per-array sha256 over the raw bytes, verified
  on load — a flipped bit or truncated file raises
  ``CheckpointCorruptError`` (the manager quarantines and falls back to
  the previous checkpoint) instead of silently loading garbage;
- **hostile names**: entry names are percent-escaped into flat
  filenames (``../x`` can no longer escape the checkpoint directory);
  the escaping is recorded per entry in ``meta.json`` so names round-
  trip exactly;
- **non-numpy dtypes**: bf16 / fp8 arrays (``ml_dtypes``) are stored as
  same-width unsigned views with the true dtype recorded in the
  manifest — ``np.save`` would otherwise degrade them to opaque void
  records that load back as raw ``V2`` bytes.

Arrays are gathered host-side at save (exact for any committed
jax.Array) and re-placed at load against the current global mesh using
each entry's recorded PartitionSpec — so a checkpoint written under one
mesh layout restores sharded under another (the reference's
merge-on-load + re-partition path, compressed into placement by spec).
``async_save`` hands the staged write + commit to a background thread,
overlapping serialization with the next training steps.

Fault-injection hooks: when ``PADDLE_CKPT_TEST_SLEEP_S`` is set (test
harnesses only) the writer emits ``CKPT_WRITE``/``CKPT_COMMIT`` marker
lines on stdout and sleeps at each, giving ``tools/faultinject.py`` a
deterministic window to SIGKILL mid-save and mid-commit.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional
from urllib.parse import quote, unquote

import jax
import numpy as np

from ..core.tensor import Tensor
from ..distributed.mesh_utils import get_global_mesh

__all__ = [
    "save_sharded", "load_sharded", "AsyncCheckpointHandle",
    "CheckpointCorruptError", "is_checkpoint_dir", "list_checkpoints",
    "load_checkpoint_extra", "checkpoint_nbytes", "prune_checkpoints",
    "quarantine_checkpoint", "sweep_stale_staging",
]

FORMAT_VERSION = 2
META_NAME = "meta.json"
EXTRA_NAME = "extra.json"
_TMP_MARK = ".tmp-"
_CORRUPT_MARK = ".corrupt-"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory is missing, truncated, or fails its
    integrity manifest — recoverable by falling back to an older one."""


def _spec_of(t) -> Optional[list]:
    spec = getattr(t, "dist_spec", None)
    return list(spec) if spec is not None else None


# ------------------------------------------------------------- metrics
def _metrics():
    """(save_ms, restore_ms, bytes_gauge) on the default registry —
    resolved lazily so importing the framework stays cheap and tests
    that reset the registry always see live families."""
    from ..observability.registry import default_registry
    reg = default_registry()
    return (
        reg.histogram("paddle_ckpt_save_ms",
                      "checkpoint save duration, snapshot to commit",
                      ("mode",)),
        reg.histogram("paddle_ckpt_restore_ms",
                      "checkpoint load duration, read to placement"),
        reg.gauge("paddle_ckpt_bytes",
                  "total bytes of the last committed checkpoint"),
    )


# ---------------------------------------------------------- test hooks
def _test_hook(stage: str, path: str):
    """Fault-injection point: with PADDLE_CKPT_TEST_SLEEP_S set, print a
    marker and sleep so an external killer can land a SIGKILL inside a
    specific save phase. Inert (two dict lookups) in production."""
    s = os.environ.get("PADDLE_CKPT_TEST_SLEEP_S")
    if not s:
        return
    import sys
    # single atomic write: the writer thread's marker must not
    # interleave mid-line with the training loop's own stdout
    sys.stdout.write(f"CKPT_{stage} {path}\n")
    sys.stdout.flush()
    time.sleep(float(s))


# ------------------------------------------------------ name / dtype io
def _fname_for(name: str) -> str:
    """Flat, filesystem-safe filename for an entry name. Separators and
    every other non-alphanumeric byte are percent-escaped, so ``../x``
    or ``a/b`` cannot traverse outside the checkpoint directory."""
    return quote(name, safe="") + ".npy"


def _check_fname(fname: str) -> str:
    """Reject manifest filenames that could escape the directory —
    covers legacy (v1) manifests where the raw name was the filename."""
    if (not fname or fname != os.path.basename(fname)
            or os.path.isabs(fname) or "/" in fname or "\\" in fname
            or fname in (".", "..")):
        raise CheckpointCorruptError(
            f"unsafe entry filename {fname!r} in checkpoint manifest")
    return fname


def _dtype_is_npy_native(dt: np.dtype) -> bool:
    """True when np.save/np.load round-trips this dtype exactly.
    ml_dtypes types (bfloat16, float8_*) serialize as anonymous void
    records and load back as raw bytes — those go through a view."""
    try:
        descr = np.lib.format.dtype_to_descr(dt)
        return np.lib.format.descr_to_dtype(descr) == dt and dt.kind != "V"
    except Exception:  # noqa: BLE001 - any descr failure => not native
        return False


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax; registers bf16/fp8 dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _write_json(path: str, obj, fsync: bool = True):
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        if fsync:
            os.fsync(f.fileno())


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without dir fds: rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ----------------------------------------------------------- the handle
class AsyncCheckpointHandle:
    """Owns the single writer thread of one async save.

    The thread is constructed and started exactly once, in ``__init__``
    (an earlier revision built a throwaway unstarted thread first, and
    ``done()`` answered True for it — a never-started thread is not
    alive). ``done()`` is truthful: it reports whether the write
    *finished*, via an event the writer sets in a ``finally``, never
    thread liveness guesses."""

    def __init__(self, target: Callable[[], object]):
        self.exception: Optional[BaseException] = None
        self.result = None
        self._finished = threading.Event()
        self._cb_lock = threading.Lock()
        self._callbacks: List[Callable] = []

        def _run():
            try:
                self.result = target()
            except BaseException as e:  # surfaced on wait()
                self.exception = e
            finally:
                self._finished.set()
                with self._cb_lock:
                    cbs, self._callbacks = self._callbacks, []
                for cb in cbs:
                    try:
                        cb(self)
                    except Exception:  # noqa: BLE001 - a broken observer
                        pass           # must not mask the save result

        self._thread = threading.Thread(
            target=_run, daemon=True, name="paddle-ckpt-writer")
        self._thread.start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the writer (bounded when ``timeout`` is given). Returns
        ``done()``; re-raises the writer's exception once finished."""
        self._thread.join(timeout)
        if self._finished.is_set() and self.exception is not None:
            raise self.exception
        return self._finished.is_set()

    def done(self) -> bool:
        return self._finished.is_set()

    def add_done_callback(self, fn: Callable):
        """Run ``fn(handle)`` on the writer thread after the save
        finishes (immediately, on the caller, if it already has)."""
        with self._cb_lock:
            if not self._finished.is_set():
                self._callbacks.append(fn)
                return
        fn(self)


# --------------------------------------------------------------- save
def _snapshot(state_dict: Dict[str, Tensor]):
    """Materialize every array to host NOW and build manifest entries.
    This runs on the caller's thread before save_sharded returns, which
    is what makes async saves donation-safe."""
    entries: Dict[str, dict] = {}
    hosts: List = []
    for name, t in state_dict.items():
        if not isinstance(name, str) or not name:
            raise ValueError(f"checkpoint entry names must be non-empty "
                             f"strings, got {name!r}")
        arr = t._data if isinstance(t, Tensor) else t
        host = np.asarray(arr)
        # the snapshot must be a PRIVATE buffer: np.asarray of a numpy
        # input returns the input itself, and of a CPU jax array can be
        # a zero-copy view of the device buffer — either way a later
        # in-place update or donation would mutate "the checkpoint"
        if host is arr or host.base is not None or \
                not host.flags["OWNDATA"]:
            host = np.array(host, copy=True)
        elif not host.flags["C_CONTIGUOUS"]:
            host = np.ascontiguousarray(host)
        dt = np.dtype(host.dtype)
        ent = {
            "shape": [int(s) for s in host.shape],
            "dtype": str(dt),
            "spec": _spec_of(t),
            "file": _fname_for(name),
            "sha256": hashlib.sha256(host.tobytes()).hexdigest(),
        }
        if not _dtype_is_npy_native(dt):
            # store a same-width unsigned view; np.save of ml_dtypes
            # arrays writes an anonymous '|V2' record that np.load
            # hands back as raw void bytes (dtype lost)
            stored = np.dtype(f"u{dt.itemsize}")
            host = host.view(stored)
            ent["stored_as"] = str(stored)
        entries[name] = ent
        hosts.append((name, host))
    return entries, hosts


def _write_and_commit(tmp_dir: str, path: str, entries, hosts, extra,
                      fsync: bool = True) -> int:
    """Write every file into the staging dir (fsync each), manifest
    last, then atomically rename the directory into place. Returns
    total bytes committed."""
    total = 0
    try:
        for name, host in hosts:
            fpath = os.path.join(tmp_dir, entries[name]["file"])
            _test_hook("WRITE", fpath)
            with open(fpath, "wb") as f:
                np.save(f, host, allow_pickle=False)
                f.flush()
                if fsync:
                    os.fsync(f.fileno())
            total += os.path.getsize(fpath)
        if extra is not None:
            epath = os.path.join(tmp_dir, EXTRA_NAME)
            _write_json(epath, extra, fsync)
            total += os.path.getsize(epath)
        mpath = os.path.join(tmp_dir, META_NAME)
        _write_json(mpath, {"format": FORMAT_VERSION, "entries": entries},
                    fsync)
        total += os.path.getsize(mpath)
        if fsync:
            _fsync_dir(tmp_dir)
        _test_hook("COMMIT", path)
        if os.path.isdir(path):
            # overwrite-in-place callers (plain save_sharded to a fixed
            # path): swap via a sidecar so readers of OTHER paths never
            # see a partial dir. The manager always uses fresh step
            # dirs, where the single os.replace below is the whole
            # commit and is atomic against any kill.
            old = path + f".old-{uuid.uuid4().hex[:8]}"
            os.replace(path, old)
            os.replace(tmp_dir, path)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp_dir, path)
        if fsync:
            _fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
    return total


def save_sharded(state_dict: Dict[str, Tensor], path: str,
                 async_save: bool = False, extra: Optional[dict] = None,
                 fsync: bool = True):
    """Write a spec-annotated checkpoint directory atomically.

    Device arrays are snapshotted to host BEFORE this returns (mutating
    or donating the source tensors afterwards cannot affect the
    checkpoint). With ``async_save`` the staged write + commit runs on
    a background thread; returns an :class:`AsyncCheckpointHandle`
    (call ``.wait()`` to surface errors / block on durability).
    ``extra`` is an optional JSON-serializable sidecar readable via
    :func:`load_checkpoint_extra`."""
    t0 = time.perf_counter()
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    entries, hosts = _snapshot(state_dict)
    tmp_dir = path + _TMP_MARK + uuid.uuid4().hex[:8]
    os.makedirs(tmp_dir)
    mode = "async" if async_save else "sync"

    def write():
        total = _write_and_commit(tmp_dir, path, entries, hosts, extra,
                                  fsync=fsync)
        try:
            save_ms, _, bytes_gauge = _metrics()
            save_ms.labels(mode).observe((time.perf_counter() - t0) * 1e3)
            bytes_gauge.set(total)
        except Exception:  # noqa: BLE001 - telemetry must never fail
            pass           # the save it measures
        return total

    if async_save:
        return AsyncCheckpointHandle(write)
    write()
    return None


# --------------------------------------------------------------- load
def _read_meta(path: str) -> Dict[str, dict]:
    mpath = os.path.join(path, META_NAME)
    try:
        with open(mpath) as f:
            meta = json.load(f)
    except FileNotFoundError as e:
        raise CheckpointCorruptError(
            f"{path}: no {META_NAME} (uncommitted or not a checkpoint "
            f"directory)") from e
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: "
                                     f"{e}") from e
    if not isinstance(meta, dict):
        raise CheckpointCorruptError(f"{path}: malformed manifest")
    if "entries" in meta:
        entries = meta["entries"]
    else:
        entries = meta  # format v1: the manifest IS the entry map
    if not isinstance(entries, dict):
        raise CheckpointCorruptError(f"{path}: malformed manifest entries")
    return entries


def load_sharded(path: str, mesh=None, verify: bool = True
                 ) -> Dict[str, Tensor]:
    """Read a checkpoint directory; place each array against ``mesh``
    (or the global mesh) by its recorded PartitionSpec. Without a mesh
    the arrays load replicated/single-device. Raises
    :class:`CheckpointCorruptError` on a missing/truncated/corrupt
    directory (``verify`` additionally checks per-array sha256)."""
    from jax.sharding import NamedSharding, PartitionSpec

    t0 = time.perf_counter()
    path = os.path.abspath(path)
    entries = _read_meta(path)
    mesh = mesh if mesh is not None else get_global_mesh()
    out = {}
    for name, ent in entries.items():
        if not isinstance(ent, dict):
            raise CheckpointCorruptError(f"{path}: malformed entry {name!r}")
        fname = _check_fname(ent.get("file") or f"{name}.npy")
        fpath = os.path.join(path, fname)
        try:
            raw = np.load(fpath, allow_pickle=False)
        except FileNotFoundError as e:
            raise CheckpointCorruptError(
                f"{path}: missing array file {fname!r} for {name!r}") from e
        except Exception as e:  # noqa: BLE001 - truncated/garbled .npy
            raise CheckpointCorruptError(
                f"{path}: unreadable array file {fname!r} for {name!r}: "
                f"{e}") from e
        if verify and "sha256" in ent:
            digest = hashlib.sha256(
                np.ascontiguousarray(raw).tobytes()).hexdigest()
            if digest != ent["sha256"]:
                raise CheckpointCorruptError(
                    f"{path}: checksum mismatch for {name!r} "
                    f"(stored {ent['sha256'][:12]}…, got {digest[:12]}…)")
        if ent.get("stored_as"):
            try:
                raw = raw.view(_resolve_dtype(ent["dtype"]))
            except Exception as e:  # noqa: BLE001
                raise CheckpointCorruptError(
                    f"{path}: cannot restore dtype {ent['dtype']!r} for "
                    f"{name!r}: {e}") from e
        if "shape" in ent and tuple(raw.shape) != tuple(ent["shape"]):
            raise CheckpointCorruptError(
                f"{path}: shape mismatch for {name!r}: manifest says "
                f"{tuple(ent['shape'])}, file holds {tuple(raw.shape)}")
        spec = ent.get("spec")
        if mesh is not None and spec is not None:
            norm = tuple(a if (a in mesh.axis_names and mesh.shape[a] > 1)
                         else None for a in spec)
            placed = jax.device_put(raw, NamedSharding(mesh,
                                                       PartitionSpec(*norm)))
        else:
            placed = jax.numpy.asarray(raw)
        t = Tensor(placed)
        if spec is not None:
            t.dist_spec = tuple(spec)
        out[name] = t
    try:
        _, restore_ms, _ = _metrics()
        restore_ms.observe((time.perf_counter() - t0) * 1e3)
    except Exception:  # noqa: BLE001
        pass
    return out


def load_checkpoint_extra(path: str) -> Optional[dict]:
    """The ``extra`` sidecar stored by ``save_sharded(extra=...)``, or
    None when the checkpoint has none."""
    epath = os.path.join(path, EXTRA_NAME)
    try:
        with open(epath) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable {EXTRA_NAME}: "
                                     f"{e}") from e


# --------------------------------------------------- directory hygiene
def is_checkpoint_dir(path: str) -> bool:
    """A committed checkpoint: a real directory holding a manifest and
    not a staging (``.tmp-``) or quarantined (``.corrupt-``) leftover."""
    base = os.path.basename(os.path.normpath(path))
    if _TMP_MARK in base or _CORRUPT_MARK in base:
        return False
    return os.path.isfile(os.path.join(path, META_NAME))


def list_checkpoints(root: str) -> List[str]:
    """Committed checkpoint directories under ``root``, oldest first by
    mtime (name as tiebreak so equal-mtime listings are stable)."""
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    found = []
    for n in sorted(names):
        p = os.path.join(root, n)
        if is_checkpoint_dir(p):
            try:
                found.append((os.path.getmtime(p), p))
            except OSError:
                continue  # racing deletion
    found.sort(key=lambda t: (t[0], t[1]))
    return [p for _, p in found]


def checkpoint_nbytes(path: str) -> int:
    total = 0
    try:
        for n in os.listdir(path):
            try:
                total += os.path.getsize(os.path.join(path, n))
            except OSError:
                pass
    except OSError:
        pass
    return total


def quarantine_checkpoint(path: str) -> Optional[str]:
    """Move a corrupt/partial checkpoint aside (never delete — the
    operator may want the forensics). Returns the new path."""
    dst = path.rstrip("/\\") + _CORRUPT_MARK + uuid.uuid4().hex[:8]
    try:
        os.replace(path, dst)
        return dst
    except OSError:
        return None


def prune_checkpoints(root: str, keep: int) -> List[str]:
    """mtime-LRU retention: delete the oldest committed checkpoints
    under ``root`` beyond the newest ``keep``. Returns deleted paths.
    ``keep <= 0`` disables pruning (keep everything)."""
    if keep <= 0:
        return []
    ckpts = list_checkpoints(root)
    dead = ckpts[:-keep] if len(ckpts) > keep else []
    removed = []
    for p in dead:
        shutil.rmtree(p, ignore_errors=True)
        if not os.path.exists(p):
            removed.append(p)
    return removed


def sweep_stale_staging(root: str, min_age_s: float = 0.0) -> List[str]:
    """Remove leftover ``.tmp-`` staging directories under ``root`` —
    the debris of writers killed mid-save. Callers must own the
    directory exclusively (the manager's single-writer-per-dir
    contract); ``min_age_s`` spares freshly-created stages."""
    removed = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return removed
    now = time.time()
    for n in names:
        if _TMP_MARK not in n:
            continue
        p = os.path.join(root, n)
        if not os.path.isdir(p):
            continue
        try:
            if min_age_s and now - os.path.getmtime(p) < min_age_s:
                continue
        except OSError:
            continue
        shutil.rmtree(p, ignore_errors=True)
        if not os.path.exists(p):
            removed.append(p)
    return removed


def decode_entry_name(fname: str) -> str:
    """Inverse of the manifest filename escaping (debugging helper)."""
    return unquote(fname[:-4] if fname.endswith(".npy") else fname)
