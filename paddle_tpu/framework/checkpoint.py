"""Sharded + async checkpointing keyed by PartitionSpec.

Reference: fleet sharded-model save utils
(/root/reference/python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_utils.py) and auto-parallel distributed save with
merge-on-load (auto_parallel/dist_saver.py); SURVEY §5.4 prescribes a
tensorstore-style sharded checkpoint for the TPU build.

Format (directory):
  meta.json                  {name: {shape, dtype, spec}}
  <name>.npy                 the FULL array (host-gathered)

Arrays are gathered host-side at save (exact for any committed jax.Array)
and re-placed at load against the current global mesh using each entry's
recorded PartitionSpec — so a checkpoint written under one mesh layout
restores sharded under another (the reference's merge-on-load +
re-partition path, compressed into placement by spec). ``async_save``
snapshots device arrays then writes on a background thread, overlapping
serialization with the next training steps.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..distributed.mesh_utils import get_global_mesh

__all__ = ["save_sharded", "load_sharded", "AsyncCheckpointHandle"]


def _spec_of(t) -> Optional[list]:
    spec = getattr(t, "dist_spec", None)
    return list(spec) if spec is not None else None


class AsyncCheckpointHandle:
    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self.exception = None

    def wait(self):
        self._thread.join()
        if self.exception is not None:
            raise self.exception

    def done(self) -> bool:
        return not self._thread.is_alive()


def save_sharded(state_dict: Dict[str, Tensor], path: str,
                 async_save: bool = False):
    """Write a spec-annotated checkpoint directory. Returns an
    AsyncCheckpointHandle when ``async_save`` (call .wait() before relying
    on the files)."""
    os.makedirs(path, exist_ok=True)
    entries = {}
    arrays = {}
    for name, t in state_dict.items():
        arr = t._data if isinstance(t, Tensor) else t
        entries[name] = {
            "shape": [int(s) for s in arr.shape],
            "dtype": str(np.dtype(arr.dtype)) if not hasattr(
                arr.dtype, "name") else arr.dtype.name,
            "spec": _spec_of(t),
        }
        arrays[name] = arr  # device handle; materialized by the writer

    def write():
        for name, arr in arrays.items():
            np.save(os.path.join(path, f"{name}.npy"), np.asarray(arr),
                    allow_pickle=False)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(entries, f, indent=1)

    if async_save:
        handle = AsyncCheckpointHandle(threading.Thread(target=write))

        def run():
            try:
                write()
            except BaseException as e:  # surfaced on wait()
                handle.exception = e

        handle._thread = threading.Thread(target=run, daemon=True)
        handle._thread.start()
        return handle
    write()
    return None


def load_sharded(path: str, mesh=None) -> Dict[str, Tensor]:
    """Read a checkpoint directory; place each array against ``mesh`` (or
    the global mesh) by its recorded PartitionSpec. Without a mesh the
    arrays load replicated/single-device."""
    from jax.sharding import NamedSharding, PartitionSpec

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    mesh = mesh if mesh is not None else get_global_mesh()
    out = {}
    for name, ent in meta.items():
        arr = np.load(os.path.join(path, f"{name}.npy"),
                      allow_pickle=False)
        spec = ent.get("spec")
        if mesh is not None and spec is not None:
            norm = tuple(a if (a in mesh.axis_names and mesh.shape[a] > 1)
                         else None for a in spec)
            placed = jax.device_put(arr, NamedSharding(mesh,
                                                       PartitionSpec(*norm)))
        else:
            placed = jax.numpy.asarray(arr)
        t = Tensor(placed)
        if spec is not None:
            t.dist_spec = tuple(spec)
        out[name] = t
    return out
