"""paddle.save / paddle.load
(reference: /root/reference/python/paddle/framework/io.py:656,898 — pickled
state_dict with per-tensor segments). Format here: a pickle where Tensors are
replaced by numpy arrays tagged with dtype/shape — readable without jax and
layout-compatible with the dict-of-arrays contract.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _pack(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj.numpy(), "name": obj.name,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", t.name)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        un = [_unpack(v, return_numpy) for v in obj]
        return un if isinstance(obj, list) else tuple(un)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
