"""paddle.signal — frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (frame/overlap_add backed by the
frame/overlap_add PHI ops, stft/istft composed from them + fft). Here the
whole pipeline is expressed as gather/scatter + jnp.fft so XLA fuses the
framing with the FFT; no custom kernels are needed on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_last(x, frame_length, hop_length):
    """x: (..., N) -> (..., frame_length, num_frames)."""
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[:, None]
           + hop_length * jnp.arange(num_frames)[None, :])  # (fl, nf)
    return x[..., idx]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice a signal into overlapping frames.

    axis=-1: (..., seq_len) -> (..., frame_length, num_frames)
    axis=0:  (seq_len, ...) -> (num_frames, frame_length, ...)
    """
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")

    def fn(a):
        if a.shape[axis if axis >= 0 else a.ndim + axis] < frame_length:
            raise ValueError(
                f"frame_length ({frame_length}) exceeds signal length")
        if axis in (-1, a.ndim - 1):
            return _frame_last(a, frame_length, hop_length)
        if axis == 0:
            moved = jnp.moveaxis(a, 0, -1)
            f = _frame_last(moved, frame_length, hop_length)
            # (..., fl, nf) -> (nf, fl, ...)
            return jnp.moveaxis(jnp.moveaxis(f, -1, 0), -1, 1)
        raise ValueError("axis must be 0 or -1")

    return apply_op("frame", fn, x)


def _overlap_add_last(x, hop_length):
    """x: (..., frame_length, num_frames) -> (..., output_len)."""
    fl, nf = x.shape[-2], x.shape[-1]
    out_len = (nf - 1) * hop_length + fl
    idx = (jnp.arange(fl)[:, None]
           + hop_length * jnp.arange(nf)[None, :])  # (fl, nf)
    out = jnp.zeros(x.shape[:-2] + (out_len,), dtype=x.dtype)
    return out.at[..., idx].add(x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of :func:`frame` (sums overlapping regions)."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")

    def fn(a):
        if a.ndim < 2:
            raise ValueError("overlap_add expects rank >= 2")
        if axis in (-1, a.ndim - 1):
            return _overlap_add_last(a, hop_length)
        if axis == 0:
            # (nf, fl, ...) -> (..., fl, nf)
            moved = jnp.moveaxis(jnp.moveaxis(a, 0, -1), 0, -2)
            return jnp.moveaxis(_overlap_add_last(moved, hop_length), -1, 0)
        raise ValueError("axis must be 0 or -1")

    return apply_op("overlap_add", fn, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform.

    x: (batch?, seq_len) real or complex -> (batch?, n_freq, num_frames).
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        win = window._data if hasattr(window, "_data") else jnp.asarray(window)
    else:
        win = jnp.ones((win_length,), dtype=jnp.float32)
    if win.shape[-1] != win_length:
        raise ValueError("window length must equal win_length")
    # center-pad the window out to n_fft, as the reference does.
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def fn(a):
        is_complex = jnp.issubdtype(a.dtype, jnp.complexfloating)
        if is_complex and onesided:
            raise ValueError(
                "stft: onesided is not supported for complex inputs")
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        frames = _frame_last(a, n_fft, hop_length)  # (..., n_fft, nf)
        frames = frames * win[:, None].astype(frames.dtype)
        if onesided and not is_complex:
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    return apply_op("stft", fn, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with least-squares window compensation."""
    if onesided and return_complex:
        raise ValueError(
            "istft: onesided=True cannot produce a complex output")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        win = window._data if hasattr(window, "_data") else jnp.asarray(window)
    else:
        win = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def fn(spec):
        s = spec
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, s.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(s, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(s, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * win[:, None].astype(frames.dtype)
        y = _overlap_add_last(frames, hop_length)
        # window-envelope normalization (sum of squared windows per sample)
        nf = spec.shape[-1]
        wsq = jnp.broadcast_to((win * win)[:, None], (n_fft, nf))
        env = _overlap_add_last(wsq, hop_length)
        y = y / jnp.maximum(env, 1e-11).astype(y.dtype)
        if center:
            y = y[..., n_fft // 2: y.shape[-1] - n_fft // 2]
        if length is not None:
            y = y[..., :length]
        return y

    return apply_op("istft", fn, x)
