"""Preemption signal handling — the SIGTERM→final-save path.

TPU pods are preempted with a SIGTERM and a short grace window before
the SIGKILL (the reference's elastic manager reacts the same way,
fleet/elastic/manager.py). This module turns that grace window into one
last committed checkpoint: ``PreemptionHandler`` installs handlers for
the configured signals, flips a process-visible flag (cooperative loops
poll ``requested()`` / ``CheckpointManager.preempted``), runs a
bounded-deadline final save through the attached manager, then chains
to the previously-installed handler so the process still terminates
with the conventional exit status.

The handler runs on the main thread (CPython delivers signals there),
so the manager uses an RLock throughout — a signal landing while the
main thread is inside a manager call must not self-deadlock.

Caveat (documented, not hidden): a save triggered mid-step captures
whatever the interpreter state is at the interrupt point. Cooperative
loops that call ``CheckpointManager.step()`` each iteration get
step-boundary saves for free — the handler's own save is the backstop
for loops that never got the chance.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Iterable, Optional

from ..framework.flags import define_flag, flag_value

__all__ = ["PreemptionHandler", "DEFAULT_PREEMPT_SIGNALS"]

define_flag("FLAGS_ckpt_preempt_deadline_s", 30.0,
            "grace budget for the preemption-triggered final checkpoint "
            "save: the SIGTERM/SIGINT handler waits at most this long "
            "for the save to commit before chaining to the previous "
            "handler (cluster schedulers SIGKILL shortly after SIGTERM; "
            "a commit that misses the window is simply a torn staging "
            "dir the next restore ignores)")

DEFAULT_PREEMPT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def _preemption_counter():
    from ..observability.registry import default_registry
    return default_registry().counter(
        "paddle_ckpt_preemptions_total",
        "preemption signals handled by PreemptionHandler",
        ("signal",))


class PreemptionHandler:
    """Installable SIGTERM/SIGINT hook: flag + bounded final save.

    ``install()`` must run on the main thread (CPython restriction on
    ``signal.signal``). ``uninstall()`` restores whatever handlers were
    there before. The handler is idempotent under signal storms: the
    final save runs once; repeat signals just re-chain."""

    def __init__(self, manager=None,
                 signals: Iterable[int] = DEFAULT_PREEMPT_SIGNALS,
                 deadline_s: Optional[float] = None,
                 chain: bool = True):
        self._manager = manager
        self._signals = tuple(signals)
        self._deadline_s = (flag_value("FLAGS_ckpt_preempt_deadline_s")
                            if deadline_s is None else float(deadline_s))
        self._chain = chain
        self._event = threading.Event()
        self._lock = threading.RLock()
        self._prev = {}
        self._installed = False
        self._saved_once = False

    # -------------------------------------------------------- install
    def install(self) -> "PreemptionHandler":
        with self._lock:
            if self._installed:
                return self
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._handle)
            self._installed = True
        return self

    def uninstall(self):
        with self._lock:
            if not self._installed:
                return
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass  # not on main thread / already torn down
            self._prev = {}
            self._installed = False

    def requested(self) -> bool:
        return self._event.is_set()

    # --------------------------------------------------------- handle
    def _handle(self, signum, frame):
        self._event.set()
        try:
            _preemption_counter().labels(
                signal.Signals(signum).name).inc()
        except Exception:  # noqa: BLE001 - telemetry only
            pass
        run_save = False
        with self._lock:
            if not self._saved_once:
                self._saved_once = True
                run_save = True
        if run_save and self._manager is not None:
            try:
                self._manager.final_save(deadline_s=self._deadline_s,
                                         reason="preempt")
            except Exception:  # noqa: BLE001 - a failing final save must
                pass           # not block process termination
        if self._chain:
            self._chain_previous(signum, frame)

    def _chain_previous(self, signum, frame):
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore the default disposition and re-deliver, so the
            # exit status is the conventional signal death (143/130)
            try:
                signal.signal(signum, signal.SIG_DFL)
            except (ValueError, OSError):
                return
            os.kill(os.getpid(), signum)
        # SIG_IGN / None: swallow, matching the prior disposition
