"""CheckpointManager — full-training-state capture with kill-9 recovery.

The elastic stance on TPU (SURVEY §5.3, ROADMAP item 4): pods are not
survivable, so elasticity = job-level restart + checkpoint resume. This
manager owns the checkpoint side of that contract on top of the
crash-safe ``framework.checkpoint`` layer:

- **full state**: parameters (+ buffers), optimizer slots, LR-scheduler
  step, global step, dataloader epoch/offset, and host+device RNG state
  — ``restore_latest()`` resumes bit-identically (the fault-injection
  harness asserts the loss trajectory of a killed-and-resumed run
  equals an uninterrupted one, bitwise);
- **off the critical path**: saves snapshot device→host synchronously
  (cheap) and stage+commit on a writer thread; at most one save is in
  flight — the next one first waits for (and accounts) the previous;
- **cadence**: step-interval (``FLAGS_ckpt_interval_steps``) or
  wall-clock (``FLAGS_ckpt_interval_s``) via ``step()``, which is the
  one call a training loop adds;
- **preemption**: ``install_preemption_handlers()`` wires SIGTERM/
  SIGINT to a bounded-deadline ``final_save`` (see ``preemption.py``);
- **recovery**: ``restore_latest()`` walks checkpoints newest→oldest,
  quarantining corrupt/partial directories (``CheckpointCorruptError``
  → ``<dir>.corrupt-*``) and falling back, so no kill point can leave
  the job unresumable while any older checkpoint survives;
- **observability**: ``paddle_ckpt_{save,restore}_ms`` histograms (from
  the framework layer), ``paddle_ckpt_bytes`` /
  ``paddle_ckpt_last_success_step`` gauges, ``paddle_ckpt_saves_total``
  / ``paddle_ckpt_corrupt_total`` / ``paddle_ckpt_steps_lost_total``
  counters, and a ``/healthz`` staleness check on the PR 3 endpoint.
  The goodput ledger is fed too: the synchronous half of every save is
  ``ckpt_save`` badput, restores are ``ckpt_restore``, and a restore's
  steps-lost count arms replay attribution — the re-run steps land in
  ``recovery`` instead of ``step``.

Steps lost on preemption are measured, not guessed: ``step()`` drops a
tiny atomic ``PROGRESS`` marker each call, and ``restore_latest()``
counts ``progress_step - restored_step`` into
``paddle_ckpt_steps_lost_total``.

Single-writer contract: one live manager per checkpoint directory
(matching the one-trainer-per-pod reality). Startup sweeps the staging
debris of any predecessor killed mid-save.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework import random as _random
from ..framework.checkpoint import (AsyncCheckpointHandle,
                                    CheckpointCorruptError,
                                    checkpoint_nbytes, list_checkpoints,
                                    load_checkpoint_extra, load_sharded,
                                    prune_checkpoints, quarantine_checkpoint,
                                    save_sharded, sweep_stale_staging)
from ..framework.flags import define_flag, flag_value
from .preemption import PreemptionHandler

__all__ = ["CheckpointManager", "RestoreResult", "latest_checkpoint"]

define_flag("FLAGS_ckpt_keep", 3,
            "checkpoints retained per directory (mtime-LRU: the oldest "
            "beyond this many committed checkpoints are deleted after "
            "each successful save; <= 0 keeps everything)")
define_flag("FLAGS_ckpt_interval_steps", 0,
            "CheckpointManager.step() saves every this many steps "
            "(0 = no step-based cadence)")
define_flag("FLAGS_ckpt_interval_s", 0.0,
            "CheckpointManager.step() saves when this many seconds "
            "passed since the last save attempt (0 = no wall-clock "
            "cadence)")
define_flag("FLAGS_ckpt_async", True,
            "stage+commit checkpoint writes on a background thread "
            "(device->host snapshot is always synchronous, so donation "
            "or in-place updates after the call never corrupt the "
            "checkpoint); off = fully synchronous saves")
define_flag("FLAGS_ckpt_staleness_s", 0.0,
            "checkpoint /healthz staleness threshold: unhealthy when "
            "the last committed checkpoint is older than this many "
            "seconds (0 = auto: 3x FLAGS_ckpt_interval_s when set, "
            "else 1800)")

_STEP_DIR_FMT = "step_{:08d}"
_PROGRESS_NAME = "PROGRESS"

_MODEL_PREFIX = "model/"
_OPT_PREFIX = "opt/"
_RNG_KEY = "rng/device_key"
_RNG_NP_KEYS = "rng/np_keys"


class RestoreResult:
    """What ``restore_latest`` recovered. ``step`` is the NEXT step to
    run (the saved global step); ``steps_lost`` is how far the dead
    process had progressed beyond it (from the PROGRESS marker)."""

    __slots__ = ("step", "epoch", "offset", "dataloader", "path",
                 "steps_lost", "restore_ms", "extra")

    def __init__(self, step, epoch, offset, dataloader, path, steps_lost,
                 restore_ms, extra):
        self.step = step
        self.epoch = epoch
        self.offset = offset
        self.dataloader = dataloader
        self.path = path
        self.steps_lost = steps_lost
        self.restore_ms = restore_ms
        self.extra = extra

    def __repr__(self):
        return (f"RestoreResult(step={self.step}, epoch={self.epoch}, "
                f"offset={self.offset}, steps_lost={self.steps_lost}, "
                f"path={self.path!r})")


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest committed checkpoint directory under ``directory`` (no
    integrity check — ``restore_latest`` does that), or None."""
    ckpts = list_checkpoints(directory)
    return ckpts[-1] if ckpts else None


class CheckpointManager:
    """Periodic, preemption-tolerant training-state checkpointing.

    Typical loop::

        mgr = CheckpointManager(dir, model=model, optimizer=opt,
                                save_interval_steps=100)
        res = mgr.restore_latest()
        start = res.step if res else 0
        mgr.install_preemption_handlers()
        for step in range(start, total):
            train_one_step(...)
            mgr.step(step + 1, epoch=epoch, offset=batch_idx)
        mgr.save(total, block=True, reason="final")
    """

    def __init__(self, directory: str, model=None, optimizer=None, *,
                 parameters: Optional[Dict[str, Tensor]] = None,
                 keep: Optional[int] = None,
                 save_interval_steps: Optional[int] = None,
                 save_interval_s: Optional[float] = None,
                 async_save: Optional[bool] = None,
                 capture_rng: bool = True,
                 dataloader_state_fn: Optional[Callable[[], dict]] = None,
                 health_check: bool = True,
                 staleness_s: Optional[float] = None,
                 clean_stale_staging: bool = True,
                 now: Callable[[], float] = time.monotonic):
        if model is None and optimizer is None and parameters is None:
            raise ValueError(
                "CheckpointManager needs at least one of model=, "
                "optimizer=, parameters= (nothing to checkpoint)")
        self.directory = os.path.abspath(directory)
        self._model = model
        self._optimizer = optimizer
        self._parameters = dict(parameters) if parameters else None
        self.keep = int(flag_value("FLAGS_ckpt_keep")
                        if keep is None else keep)
        self.save_interval_steps = int(
            flag_value("FLAGS_ckpt_interval_steps")
            if save_interval_steps is None else save_interval_steps)
        self.save_interval_s = float(
            flag_value("FLAGS_ckpt_interval_s")
            if save_interval_s is None else save_interval_s)
        self.async_save = bool(flag_value("FLAGS_ckpt_async")
                               if async_save is None else async_save)
        self._capture_rng = capture_rng
        self._dataloader_state_fn = dataloader_state_fn
        self._now = now
        # a signal handler interrupting the main thread mid-call must be
        # able to re-enter (final_save while step() holds the lock)
        self._lock = threading.RLock()
        self._inflight: Optional[AsyncCheckpointHandle] = None
        self._inflight_step = -1
        self._inflight_t0 = 0.0
        self._last_attempt_time: Optional[float] = None
        self._last_success_step = -1
        self._last_success_walltime: Optional[float] = None
        self._last_error: Optional[BaseException] = None
        self._last_seen = {"step": -1, "epoch": None, "offset": None,
                           "dataloader": None}
        self._preemption: Optional[PreemptionHandler] = None
        self._health_name: Optional[str] = None
        self._staleness_s = staleness_s

        os.makedirs(self.directory, exist_ok=True)
        if clean_stale_staging:
            sweep_stale_staging(self.directory)

        from ..observability.registry import default_registry
        reg = default_registry()
        self._m_last_step = reg.gauge(
            "paddle_ckpt_last_success_step",
            "global step of the last committed checkpoint")
        self._m_saves = reg.counter(
            "paddle_ckpt_saves_total",
            "checkpoint save attempts by outcome", ("result",))
        self._m_corrupt = reg.counter(
            "paddle_ckpt_corrupt_total",
            "checkpoint directories quarantined as corrupt on restore")
        self._m_steps_lost = reg.counter(
            "paddle_ckpt_steps_lost_total",
            "training steps re-run after restore because they "
            "post-dated the last committed checkpoint")
        if health_check:
            self.enable_health_check()

    # ----------------------------------------------------------- state
    def _capture(self, step: int, epoch, offset, dataloader_state,
                 reason: str):
        """(arrays, extra) for one checkpoint. Arrays stay device-side
        here — save_sharded host-snapshots them before returning."""
        arrays: Dict[str, object] = {}
        extra: Dict[str, object] = {
            "train": {"step": int(step),
                      "epoch": None if epoch is None else int(epoch),
                      "offset": None if offset is None else int(offset),
                      "wall_time": time.time(),
                      "reason": reason},
        }
        if self._model is not None:
            for k, v in self._model.state_dict().items():
                arrays[_MODEL_PREFIX + k] = v
        if self._parameters is not None:
            for k, v in self._parameters.items():
                arrays[_MODEL_PREFIX + k] = v
        if self._optimizer is not None:
            opt_scalars: Dict[str, object] = {}
            for k, v in self._optimizer.state_dict().items():
                if isinstance(v, Tensor):
                    arrays[_OPT_PREFIX + k] = v
                else:  # "@step" int, "LR_Scheduler" dict — JSON-able
                    opt_scalars[k] = v
            extra["optimizer"] = opt_scalars
            params = self._optimizer._parameters or []
            # accumulator keys embed parameter NAMES; record the order
            # so restore can remap onto a live optimizer whose params
            # were minted with different auto-names (same architecture,
            # different name counter — the in-process restore case)
            extra["optimizer_param_names"] = [
                getattr(p, "name", "") for p in params]
        if self._capture_rng:
            seed, counter, key_data = _random.default_generator().get_state()
            arrays[_RNG_KEY] = np.asarray(key_data)
            np_state = np.random.get_state()
            arrays[_RNG_NP_KEYS] = np.asarray(np_state[1])
            extra["rng"] = {"seed": int(seed), "counter": int(counter),
                            "np": [np_state[0], int(np_state[2]),
                                   int(np_state[3]), float(np_state[4])]}
        if dataloader_state is None and self._dataloader_state_fn is not None:
            dataloader_state = self._dataloader_state_fn()
        if dataloader_state is not None:
            extra["dataloader"] = dataloader_state
        return arrays, extra

    def _apply(self, loaded: Dict[str, Tensor], extra: dict):
        if self._model is not None:
            model_sd = {k[len(_MODEL_PREFIX):]: v for k, v in loaded.items()
                        if k.startswith(_MODEL_PREFIX)}
            if model_sd:
                self._model.set_state_dict(model_sd)
        if self._parameters is not None:
            for k, p in self._parameters.items():
                v = loaded.get(_MODEL_PREFIX + k)
                if v is not None:
                    p.set_value(v.numpy())
        if self._optimizer is not None:
            opt_sd: Dict[str, object] = {
                k[len(_OPT_PREFIX):]: v for k, v in loaded.items()
                if k.startswith(_OPT_PREFIX)}
            opt_sd.update(extra.get("optimizer") or {})
            saved_names = extra.get("optimizer_param_names")
            cur = self._optimizer._parameters or []
            accums = getattr(self._optimizer, "_accum_names", [])
            if saved_names and len(saved_names) == len(cur):
                # remap slot keys from saved param names to the live
                # ones by position (identical names = no-op rename);
                # exact `<param>_<accum>` matches only, so one name
                # being a prefix of another cannot mis-route a slot
                rename = {}
                for i, old in enumerate(saved_names):
                    cur_name = getattr(cur[i], "name", "")
                    if not old or not cur_name:
                        continue
                    for acc in accums:
                        rename[f"{old}_{acc}"] = f"{cur_name}_{acc}"
                opt_sd = {rename.get(k, k): v for k, v in opt_sd.items()}
            self._optimizer.set_state_dict(opt_sd)
        rng = extra.get("rng")
        if self._capture_rng and rng is not None and _RNG_KEY in loaded:
            key_data = np.asarray(loaded[_RNG_KEY].numpy())
            _random.default_generator().set_state(
                (int(rng["seed"]), int(rng["counter"]), key_data))
            np_meta = rng.get("np")
            if np_meta is not None and _RNG_NP_KEYS in loaded:
                keys = np.asarray(loaded[_RNG_NP_KEYS].numpy())
                np.random.set_state((np_meta[0], keys, int(np_meta[1]),
                                     int(np_meta[2]), float(np_meta[3])))

    # ------------------------------------------------------------ save
    def step(self, step: int, epoch: Optional[int] = None,
             offset: Optional[int] = None,
             dataloader_state: Optional[dict] = None
             ) -> Optional[AsyncCheckpointHandle]:
        """Per-step hook: records progress (the steps-lost witness) and
        saves when the step/wall-clock cadence says so. Returns the
        in-flight handle when a save started."""
        with self._lock:
            self._last_seen = {"step": int(step), "epoch": epoch,
                               "offset": offset,
                               "dataloader": dataloader_state}
        self._write_progress(step)
        if not self._should_save(step):
            return None
        return self.save(step, epoch=epoch, offset=offset,
                         dataloader_state=dataloader_state)

    def _should_save(self, step: int) -> bool:
        if self.save_interval_steps > 0 and step > 0 and \
                step % self.save_interval_steps == 0:
            return True
        if self.save_interval_s > 0:
            with self._lock:
                last = self._last_attempt_time
            if last is None or self._now() - last >= self.save_interval_s:
                return True
        return False

    def _write_progress(self, step: int):
        """Tiny atomic marker: how far training actually got. Read back
        on restore to count steps lost to the kill. No fsync — it is a
        hint, and a torn replace is impossible."""
        import json
        tmp = os.path.join(self.directory,
                           f".{_PROGRESS_NAME}.tmp.{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump({"step": int(step), "wall_time": time.time()}, f)
            os.replace(tmp, os.path.join(self.directory, _PROGRESS_NAME))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _read_progress(self) -> Optional[int]:
        import json
        try:
            with open(os.path.join(self.directory, _PROGRESS_NAME)) as f:
                return int(json.load(f)["step"])
        except Exception:  # noqa: BLE001 - absent/torn marker: no info
            return None

    def save(self, step: int, epoch: Optional[int] = None,
             offset: Optional[int] = None,
             dataloader_state: Optional[dict] = None,
             block: bool = False, reason: str = "interval"
             ) -> Optional[AsyncCheckpointHandle]:
        """Checkpoint the full training state at ``step``. Async by
        default (manager policy): snapshots host-side now, commits on
        the writer thread. A previous in-flight save is awaited first —
        at most one writer at a time, and save errors are recorded (in
        metrics + ``last_error``) rather than raised, so a sick
        filesystem degrades durability, not training."""
        self.wait()  # errors from the previous save land in _last_error
        # goodput: only the SYNCHRONOUS part of a save (state capture +
        # device->host snapshot, plus the full write when blocking)
        # stalls training; the async writer thread runs alongside steps
        # and is deliberately not badput
        from ..observability.goodput import default_ledger
        ledger = default_ledger()
        ledger.begin("ckpt_save")
        try:
            arrays, extra = self._capture(step, epoch, offset,
                                          dataloader_state, reason)
            path = os.path.join(self.directory,
                                _STEP_DIR_FMT.format(int(step)))
            t0 = self._now()
            with self._lock:
                self._last_attempt_time = t0
            use_async = self.async_save and not block
            try:
                handle = save_sharded(arrays, path, async_save=use_async,
                                      extra=extra)
            except Exception as e:  # noqa: BLE001 - record, don't kill
                self._record_save_result(step, error=e)  # training
                return None
        finally:
            ledger.end()
        if handle is None:
            self._record_save_result(step, error=None)
            return None
        with self._lock:
            self._inflight = handle
            self._inflight_step = int(step)
        handle.add_done_callback(self._on_save_done)
        return handle

    def _on_save_done(self, handle: AsyncCheckpointHandle):
        with self._lock:
            if self._inflight is handle:
                self._inflight = None
            step = self._inflight_step
        self._record_save_result(step, error=handle.exception)

    def _record_save_result(self, step: int,
                            error: Optional[BaseException]):
        if error is not None:
            with self._lock:
                self._last_error = error
            self._m_saves.labels("error").inc()
            return
        with self._lock:
            self._last_error = None
            self._last_success_step = int(step)
            self._last_success_walltime = time.time()
        self._m_saves.labels("ok").inc()
        self._m_last_step.set(int(step))
        if self.keep > 0:
            prune_checkpoints(self.directory, self.keep)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until no save is in flight. Unlike the raw handle,
        never raises — writer errors are folded into save accounting by
        the done callback. Returns False if still in flight."""
        with self._lock:
            handle = self._inflight
        if handle is None:
            return True
        try:
            return handle.wait(timeout)
        except BaseException:  # noqa: BLE001 - already recorded by the
            return True        # done callback

    def final_save(self, deadline_s: Optional[float] = None,
                   reason: str = "preempt") -> bool:
        """Bounded-deadline last save (the preemption path). Saves the
        most recently seen step unless it is already committed; waits at
        most ``deadline_s`` for the commit. Returns True when the state
        is committed durable."""
        budget = float("inf") if deadline_s is None else float(deadline_s)
        t_end = self._now() + budget
        with self._lock:
            seen = dict(self._last_seen)
            done_step = self._last_success_step
            inflight = self._inflight
            inflight_step = self._inflight_step
        step = seen["step"]
        if step < 0:
            return False  # never stepped: nothing meaningful to save
        if done_step == step:
            return True   # already durable
        if inflight is not None and inflight_step == step:
            return inflight.wait(max(0.0, t_end - self._now())) and \
                inflight.exception is None
        handle = self.save(step, epoch=seen["epoch"],
                           offset=seen["offset"],
                           dataloader_state=seen["dataloader"],
                           reason=reason)
        if handle is None:  # sync save (or failed: last_error records it)
            with self._lock:
                return self._last_success_step == step
        ok = handle.wait(max(0.0, t_end - self._now()))
        return ok and handle.exception is None

    # --------------------------------------------------------- restore
    def restore_latest(self, mesh=None) -> Optional[RestoreResult]:
        """Load the newest intact checkpoint into the attached model/
        optimizer/RNG and return its metadata. Corrupt or partial
        directories are quarantined (``<dir>.corrupt-*``) and skipped —
        after any kill, some checkpoint loads or None is returned (the
        caller starts fresh)."""
        from ..observability.goodput import default_ledger
        ledger = default_ledger()
        self.wait()
        progress = self._read_progress()
        t0 = self._now()
        for path in reversed(list_checkpoints(self.directory)):
            try:
                with ledger.timed("ckpt_restore"):
                    loaded = load_sharded(path, mesh=mesh)
                    extra = load_checkpoint_extra(path) or {}
                    self._apply(loaded, extra)
            except CheckpointCorruptError:
                self._m_corrupt.inc()
                quarantine_checkpoint(path)
                continue
            train = extra.get("train") or {}
            step = int(train.get("step", -1))
            restore_ms = (self._now() - t0) * 1e3
            steps_lost = max(0, progress - step) \
                if (progress is not None and step >= 0) else 0
            if steps_lost:
                self._m_steps_lost.inc(steps_lost)
                # the next steps_lost step frames are replayed work —
                # MegaScale's preemption-recovery badput, not goodput
                ledger.arm_replay(steps_lost)
            with self._lock:
                self._last_success_step = step
                self._last_success_walltime = time.time()
            if step >= 0:
                self._m_last_step.set(step)
            return RestoreResult(
                step=step, epoch=train.get("epoch"),
                offset=train.get("offset"),
                dataloader=extra.get("dataloader"),
                path=path, steps_lost=steps_lost,
                restore_ms=restore_ms, extra=extra)
        return None

    # ------------------------------------------------------ preemption
    def install_preemption_handlers(self, signals=None,
                                    deadline_s: Optional[float] = None
                                    ) -> PreemptionHandler:
        """Wire SIGTERM/SIGINT to a bounded final save (then chain to
        the previous handler, so default termination still happens)."""
        from .preemption import DEFAULT_PREEMPT_SIGNALS
        handler = PreemptionHandler(
            manager=self,
            signals=DEFAULT_PREEMPT_SIGNALS if signals is None else signals,
            deadline_s=deadline_s)
        handler.install()
        with self._lock:
            self._preemption = handler
        return handler

    @property
    def preempted(self) -> bool:
        """True once a preemption signal arrived (cooperative loops
        should drain and exit)."""
        with self._lock:
            handler = self._preemption
        return handler.requested() if handler is not None else False

    # ---------------------------------------------------------- health
    def enable_health_check(self, staleness_s: Optional[float] = None):
        """Register checkpoint staleness on the shared /healthz: fails
        when the last committed checkpoint is older than the threshold
        (or when the most recent save attempt errored)."""
        from ..observability.httpd import add_health_check
        if staleness_s is not None:
            self._staleness_s = float(staleness_s)
        name = f"checkpoint:{os.path.basename(self.directory)}"
        add_health_check(name, self._health)
        with self._lock:
            self._health_name = name

    def _staleness_threshold(self) -> float:
        if self._staleness_s:
            return float(self._staleness_s)
        flagged = float(flag_value("FLAGS_ckpt_staleness_s"))
        if flagged > 0:
            return flagged
        if self.save_interval_s > 0:
            return 3.0 * self.save_interval_s
        return 1800.0

    def _health(self):
        with self._lock:
            err = self._last_error
            last_wall = self._last_success_walltime
            last_step = self._last_success_step
        if err is not None:
            return False, {"last_error": repr(err),
                           "last_success_step": last_step}
        if last_wall is None:
            return True, {"state": "no checkpoint yet"}
        age = time.time() - last_wall
        limit = self._staleness_threshold()
        return age <= limit, {"last_success_step": last_step,
                              "age_s": round(age, 3),
                              "staleness_limit_s": round(limit, 3)}

    # --------------------------------------------------------- teardown
    @property
    def last_success_step(self) -> int:
        with self._lock:
            return self._last_success_step

    @property
    def last_error(self) -> Optional[BaseException]:
        with self._lock:
            return self._last_error

    def close(self):
        """Flush the in-flight save, uninstall signal handlers, and
        drop the health check."""
        self.wait()
        with self._lock:
            handler = self._preemption
            self._preemption = None
            health_name = self._health_name
            self._health_name = None
        if handler is not None:
            handler.uninstall()
        if health_name is not None:
            from ..observability.httpd import remove_health_check
            remove_health_check(health_name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
