"""paddle_tpu.elastic — preemption-tolerant training (ROADMAP item 4).

Production TPU pods get preempted; the elastic stance here (SURVEY
§5.3/§5.4) is job-level restart + bit-identical checkpoint resume:

- :class:`CheckpointManager` captures the FULL training state — params,
  optimizer slots, LR-scheduler step, global step, dataloader
  epoch/offset, host+device RNG — on a step or wall-clock cadence, off
  the critical path, onto the crash-safe atomic checkpoint layer in
  ``framework.checkpoint`` (staged ``.tmp`` dirs, per-file fsync,
  manifest-commit rename: a ``kill -9`` at any instant leaves either
  the previous or the new checkpoint fully intact);
- :class:`PreemptionHandler` turns SIGTERM/SIGINT into one last
  bounded-deadline save before conventional termination;
- ``restore_latest()`` quarantines corrupt/partial directories and
  falls back, so recovery never dead-ends on save debris;
- save/restore latency, bytes, last-success step, and steps lost on
  preemption all land on the shared metric registry, with a
  checkpoint-staleness check on ``/healthz``.

Paired tooling: ``tools/faultinject.py`` SIGKILLs a real training
subprocess mid-step / mid-save / mid-commit and asserts the resumed
loss trajectory is bitwise identical to an uninterrupted run.
"""
from .manager import (CheckpointManager, RestoreResult,  # noqa: F401
                      latest_checkpoint)
from .preemption import (DEFAULT_PREEMPT_SIGNALS,  # noqa: F401
                         PreemptionHandler)
from ..framework.checkpoint import (AsyncCheckpointHandle,  # noqa: F401
                                    CheckpointCorruptError,
                                    list_checkpoints, load_sharded,
                                    prune_checkpoints, save_sharded)

__all__ = [
    "CheckpointManager", "RestoreResult", "latest_checkpoint",
    "PreemptionHandler", "DEFAULT_PREEMPT_SIGNALS",
    "AsyncCheckpointHandle", "CheckpointCorruptError",
    "list_checkpoints", "load_sharded", "prune_checkpoints",
    "save_sharded",
]
