"""paddle.jit: to_static / save / load.

Reference: /root/reference/python/paddle/jit/api.py:222 (to_static via AST
rewriting + ProgramTranslator). TPU-native design: to_static = trace the
layer/function with jax.jit via functionalization (jit/functional.py) — the
jax idiom — with the whole traced program exposed to eager autograd as a
single op (one jax.vjp over the compiled function), so ``loss.backward()``
still works through a to_static model.
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Optional

import jax
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from .functional import functional_call, state_arrays


class StaticFunction:
    def __init__(self, function, input_spec=None, layer: Optional[Layer] = None):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_fn = None
        self.concrete_programs = []
        # persistent-compile-cache memo: signature -> loaded AOT
        # executable (False = failed, use the jit path); cleared
        # whenever the traced function changes (_build_jit)
        self._exec_memo = {}
        self._fn_fp = None
        # xstats memo: (training, operand shapes) -> ExecEntry
        self._xstats_memo = {}

    def _build_jit(self):
        self._exec_memo = {}
        self._fn_fp = None
        self._xstats_memo = {}
        layer = self._layer

        if layer is not None:
            fwd = self._function

            def raw(params, buffers, *arrays, _training=True):
                prev = layer.training
                layer.training = _training
                for sub in layer.sublayers():
                    sub.training = _training
                try:
                    from ..core import autograd as ag
                    from .functional import _swapped_state
                    with _swapped_state(layer, params, buffers), ag.no_grad():
                        t_args = [Tensor(a, stop_gradient=True)
                                  if isinstance(a, jax.Array) else a
                                  for a in arrays]
                        out = fwd(*t_args)
                    return jax.tree_util.tree_map(
                        lambda x: x._data if isinstance(x, Tensor) else x, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                finally:
                    layer.training = prev
                    for sub in layer.sublayers():
                        sub.training = prev
            self._jit_fn = jax.jit(raw, static_argnames=("_training",))
        else:
            fn = self._function

            def raw(*arrays):
                from ..core import autograd as ag
                with ag.no_grad():
                    t_args = [Tensor(a, stop_gradient=True)
                              if isinstance(a, jax.Array) else a
                              for a in arrays]
                    out = fn(*t_args)
                return jax.tree_util.tree_map(
                    lambda x: x._data if isinstance(x, Tensor) else x, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
            self._jit_fn = jax.jit(raw)

    def __call__(self, *args, **kwargs):
        if self._jit_fn is None:
            self._build_jit()
        try:
            return self._invoke(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - inspect & re-raise below
            from .dy2static import ast_transform, convert_call_guard
            if not convert_call_guard(e) or \
                    getattr(self._function, "__dy2static_transformed__",
                            False):
                raise
            # tensor-dependent Python control flow broke the trace: rewrite
            # the source (if/while → lax-able cond/while_loop) and retrace —
            # the reference's AST-transformer path
            # (/root/reference/python/paddle/jit/dy2static/), applied lazily
            # only when the fast trace path cannot convert.
            self._function = ast_transform(self._function)
            self._jit_fn = None
            self._build_jit()
            return self._invoke(*args, **kwargs)

    def _will_record(self, tensors) -> bool:
        """Mirror of apply_op's capture conditions: True when the call
        will be traced through later — differentiated (vjp through the
        callee) or recorded into a static Program for jitted replay —
        in which case a non-traceable AOT executable must not be
        substituted for the jitted function."""
        from ..amp.auto_cast import amp_state
        from ..core import autograd as ag
        from ..static import program as static_program
        if static_program.in_static_mode():
            # apply_op records the callee into the Program; Executor.run
            # later replays it under jax.jit, and tracing through a
            # jax.stages.Compiled raises — record the traceable jit fn
            return True
        if amp_state() is not None:
            # autocast may rewrite operand dtypes at the dispatch
            # boundary, invalidating the shape/dtype key the cached
            # executable was compiled for
            return True
        return ag.grad_enabled() and any(not t.stop_gradient
                                         for t in tensors)

    def _invoke(self, *args, **kwargs):
        arrays = [a._data if isinstance(a, Tensor) else a for a in args]
        if self._layer is not None:
            params, buffers = state_arrays(self._layer)
            training = self._layer.training
            param_tensors = [p for _, p in self._layer.named_parameters()]

            # Expose the whole compiled program as ONE differentiable op so
            # eager .backward() flows into the parameters.
            param_names = list(params.keys())

            tensor_args = [t if isinstance(t, Tensor) else Tensor(t)
                           for t in args]
            exec_fn = None
            if not self._will_record([*param_tensors, *tensor_args]):
                exec_fn = self._cached_exec(
                    params, buffers, [t._data for t in tensor_args],
                    training)
            self._xstats_note(params, buffers,
                              [t._data for t in tensor_args], training,
                              exec_fn)

            def one_op(*all_arrays):
                p_arrays = dict(zip(param_names,
                                    all_arrays[:len(param_names)]))
                in_arrays = all_arrays[len(param_names):]
                if exec_fn is not None:
                    return exec_fn(p_arrays, buffers, *in_arrays)
                return self._jit_fn(p_arrays, buffers, *in_arrays,
                                    _training=training)

            return apply_op("jit_program", one_op, *param_tensors,
                            *tensor_args)
        t_args = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
        exec_fn = None
        if not self._will_record(t_args):
            exec_fn = self._cached_exec(None, None,
                                        [t._data for t in t_args], False)
        self._xstats_note(None, None, [t._data for t in t_args], False,
                          exec_fn)
        fn = exec_fn if exec_fn is not None else self._jit_fn
        return apply_op("jit_program", lambda *arrs: fn(*arrs), *t_args)

    def _cached_exec(self, params, buffers, arrays, training):
        """Persistent-cache tier for the non-differentiating call path:
        a loaded (or compiled + stored) AOT executable for this operand
        signature, or None. A hit skips both the Python retrace and the
        XLA compile a fresh process would otherwise pay."""
        import jax

        from ..framework.flags import flag_value, flags_generation
        if not str(flag_value("FLAGS_compile_cache_dir") or ""):
            return None
        # flags_generation: any set_flags call (a compile-relevant flag
        # flip, a repointed cache dir) invalidates the memo — the next
        # call re-derives the key and re-consults the cache
        sig = (flags_generation(), bool(training), tuple(
            (tuple(getattr(a, "shape", ())),
             str(getattr(a, "dtype", type(a).__name__)))
            for a in jax.tree_util.tree_leaves((params, buffers, arrays))))
        memo = self._exec_memo
        if sig in memo:
            fn = memo[sig]
            return fn if fn is not False else None
        fn = None
        try:
            from .. import compile_cache as cc
            cache = cc.default_cache()
            if cache is not None:
                fp = self._fn_fp
                if fp is None:
                    parts = [cc.function_fingerprint(self._function)]
                    if self._layer is not None:
                        parts.append(cc.layer_fingerprint(self._layer))
                    fp = self._fn_fp = cc.bytes_fingerprint(
                        "\n".join(parts).encode())
                key, kparts = cc.cache_key(
                    fp, (params, buffers, arrays),
                    extra={"site": "to_static",
                           "training": bool(training)})
                if self._layer is not None:
                    def build():
                        return self._jit_fn.lower(
                            params, buffers, *arrays,
                            _training=training).compile()
                else:
                    def build():
                        return self._jit_fn.lower(*arrays).compile()
                fn, _hit = cache.get_or_compile(
                    key, build, site="jit", meta=kparts,
                    xstats_meta=self._xstats_meta(params, buffers,
                                                  arrays, training))
        except Exception:  # noqa: BLE001 - any cache/AOT failure falls
            fn = None      # back to the jitted dispatch
        memo[sig] = fn if fn is not None else False
        return fn

    # ------------------------------------------------- xstats wiring
    @staticmethod
    def _xstats_signature(params, buffers, arrays, training) -> tuple:
        from ..observability import xstats
        return ((((int(bool(training)),), "training"),)
                + xstats.signature_of((params, buffers, arrays)))

    def _xstats_meta(self, params, buffers, arrays, training):
        """xstats registration payload: identity + a lower thunk over
        abstract operand specs (computed lazily at scrape time)."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return None
            spec = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    tuple(getattr(a, "shape", ())), a.dtype),
                (params, buffers, arrays))
            jit_fn = self._jit_fn
            if self._layer is not None:
                def thunk():
                    return jit_fn.lower(spec[0], spec[1], *spec[2],
                                        _training=training)
            else:
                def thunk():
                    return jit_fn.lower(*spec[2])
            return {"kind": "jit",
                    "signature": self._xstats_signature(
                        params, buffers, arrays, training),
                    "fingerprint": self._fn_fp,
                    "lower_thunk": thunk}
        except Exception:  # noqa: BLE001 - observability is garnish
            return None

    def _xstats_note(self, params, buffers, arrays, training, exec_fn):
        """Per-call dispatch note (memoized by operand shapes)."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return
            memo_key = (bool(training), tuple(
                (tuple(getattr(a, "shape", ())),
                 str(getattr(a, "dtype", ""))) for a in arrays))
            ent = self._xstats_memo.get(memo_key)
            if ent is None:
                sig = self._xstats_signature(params, buffers, arrays,
                                             training)
                if exec_fn is not None:
                    ent = xstats.register_executable("jit", sig)
                else:
                    meta = self._xstats_meta(params, buffers, arrays,
                                             training) or {}
                    ent = xstats.register_executable(
                        "jit", sig, kind="jit",
                        fingerprint=meta.get("fingerprint"),
                        provenance={"cache": "off"},
                        lower_thunk=meta.get("lower_thunk"))
                if ent is None:
                    return
                self._xstats_memo[memo_key] = ent
            xstats.note_dispatch(ent)
        except Exception:  # noqa: BLE001 - never break a jit call
            pass

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = sf
            return fn
        if hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
            return StaticFunction(fn, input_spec, layer=fn.__self__)
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    return fn


def _specs_from_input_spec(input_spec):
    """InputSpec/Tensor/array list -> jax.ShapeDtypeStructs + names.

    Dynamic dims (None / -1, the paddle variable-batch idiom) become
    jax.export symbolic dimensions, so the exported StableHLO accepts any
    extent there (shape polymorphism; one shared SymbolicScope)."""
    from jax import export as jexport

    from ..framework import dtype as dtype_mod

    specs, names = [], []
    scope = None
    n_dyn = 0
    for i, s in enumerate(input_spec):
        if isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(s._data.shape, s._data.dtype))
            names.append(getattr(s, "name", None) or f"feed_{i}")
        elif hasattr(s, "shape"):  # InputSpec or ndarray
            dims = []
            for d in s.shape:
                if d is None or (isinstance(d, int) and d < 0):
                    dims.append(f"_dyn{n_dyn}")
                    n_dyn += 1
                else:
                    dims.append(str(int(d)))
            if n_dyn and scope is None:
                scope = jexport.SymbolicScope()
            shape = tuple(jexport.symbolic_shape(",".join(dims), scope=scope)
                          if scope is not None else
                          tuple(int(d) for d in dims))
            dt = dtype_mod.to_jax_dtype(getattr(s, "dtype", "float32"))
            specs.append(jax.ShapeDtypeStruct(shape, dt))
            names.append(getattr(s, "name", None) or f"feed_{i}")
        else:
            raise TypeError(f"unsupported input_spec entry: {s!r}")
    return specs, names


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — export the traced forward as a StableHLO artifact
    (+ weights) loadable in a fresh process by ``jit.load`` or the inference
    Predictor. Reference contract: paddle.jit.save → pdmodel/pdiparams
    (/root/reference/python/paddle/jit/api.py:222, fluid/jit/serializer.cc);
    here the program format is serialized StableHLO (framework/exporting.py).
    """
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    if input_spec is None:
        raise ValueError(
            "jit.save requires input_spec=[InputSpec(shape, dtype), ...] "
            "(or example Tensors) to trace the forward for export")
    from ..framework.exporting import export_artifact

    specs, names = _specs_from_input_spec(input_spec)
    params, buffers = state_arrays(layer)
    # materialize to host: weights trained under a mesh are committed to
    # multi-device shardings, and any such array reaching the export trace
    # (e.g. as a closure constant) conflicts with the single-device serving
    # arguments; np.asarray gathers the global value
    import numpy as _np
    params = {n: _np.asarray(v) for n, v in params.items()}
    buffers = {n: _np.asarray(v) for n, v in buffers.items()}
    weights = {**{f"p.{n}": v for n, v in params.items()},
               **{f"b.{n}": v for n, v in buffers.items()}}
    wnames = sorted(weights)

    def run(weight_list, *inputs):
        w = dict(zip(wnames, weight_list))
        p = {n[2:]: a for n, a in w.items() if n.startswith("p.")}
        b = {n[2:]: a for n, a in w.items() if n.startswith("b.")}
        return functional_call(layer, p, b, *inputs, training=False)

    # reference wire format (.pdmodel ProgramDesc + .pdiparams) FIRST so
    # models trained here deploy to Paddle Inference / paddle2onnx
    # consumers — and so the .pdexec written after it is never older than
    # the .pdmodel of the same export (pdexec_is_stale would otherwise
    # flag every fresh save)
    if configs.get("pdmodel_format", True):
        from ..static.pdmodel_export import save_pdmodel_or_warn
        save_pdmodel_or_warn(path, run, weights, specs, names)
    export_artifact(path, run, weights, specs, feed_names=names)


class TranslatedLayer(Layer):
    """Inference-only layer reconstructed from a saved artifact
    (reference: TranslatedLayer from paddle.jit.load). Parameters are real
    so ``state_dict`` works; forward runs the AOT StableHLO program (no
    autograd through it — retrain from the original Python class)."""

    def __init__(self, artifact):
        super().__init__()
        self._artifact = artifact
        for wname, arr in artifact.weights.items():
            safe = wname.replace(".", "__")
            p = Parameter(jax.numpy.asarray(arr), trainable=False)
            p.name = wname
            setattr(self, safe, p)

    def forward(self, *args):
        arrays = [a._data if isinstance(a, Tensor) else Tensor(a)._data
                  for a in args]
        # pick up any state_dict mutations since load
        self._artifact.set_weights(
            {p.name: p._data for p in self.parameters()})
        out = self._artifact(*arrays)
        return jax.tree_util.tree_map(Tensor, out)


def load(path, **configs):
    """paddle.jit.load — reconstruct a servable layer in a fresh process."""
    from ..framework.exporting import load_artifact

    return TranslatedLayer(load_artifact(path))


def enable_to_static(flag=True):
    global _to_static_enabled
    _to_static_enabled = flag


_to_static_enabled = True


def ignore_module(modules):
    pass
