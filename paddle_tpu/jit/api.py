"""paddle.jit: to_static / save / load.

Reference: /root/reference/python/paddle/jit/api.py:222 (to_static via AST
rewriting + ProgramTranslator). TPU-native design: to_static = trace the
layer/function with jax.jit via functionalization (jit/functional.py) — the
jax idiom — with the whole traced program exposed to eager autograd as a
single op (one jax.vjp over the compiled function), so ``loss.backward()``
still works through a to_static model.
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Optional

import jax
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from .functional import functional_call, state_arrays


class StaticFunction:
    def __init__(self, function, input_spec=None, layer: Optional[Layer] = None):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_fn = None
        self.concrete_programs = []

    def _build_jit(self):
        layer = self._layer

        if layer is not None:
            fwd = self._function

            def raw(params, buffers, *arrays, _training=True):
                prev = layer.training
                layer.training = _training
                for sub in layer.sublayers():
                    sub.training = _training
                try:
                    from ..core import autograd as ag
                    from .functional import _swapped_state
                    with _swapped_state(layer, params, buffers), ag.no_grad():
                        t_args = [Tensor(a, stop_gradient=True)
                                  if isinstance(a, jax.Array) else a
                                  for a in arrays]
                        out = fwd(*t_args)
                    return jax.tree_util.tree_map(
                        lambda x: x._data if isinstance(x, Tensor) else x, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                finally:
                    layer.training = prev
                    for sub in layer.sublayers():
                        sub.training = prev
            self._jit_fn = jax.jit(raw, static_argnames=("_training",))
        else:
            fn = self._function

            def raw(*arrays):
                from ..core import autograd as ag
                with ag.no_grad():
                    t_args = [Tensor(a, stop_gradient=True)
                              if isinstance(a, jax.Array) else a
                              for a in arrays]
                    out = fn(*t_args)
                return jax.tree_util.tree_map(
                    lambda x: x._data if isinstance(x, Tensor) else x, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
            self._jit_fn = jax.jit(raw)

    def __call__(self, *args, **kwargs):
        if self._jit_fn is None:
            self._build_jit()
        arrays = [a._data if isinstance(a, Tensor) else a for a in args]
        if self._layer is not None:
            params, buffers = state_arrays(self._layer)
            training = self._layer.training
            param_tensors = [p for _, p in self._layer.named_parameters()]

            # Expose the whole compiled program as ONE differentiable op so
            # eager .backward() flows into the parameters.
            param_names = list(params.keys())

            def one_op(*all_arrays):
                p_arrays = dict(zip(param_names,
                                    all_arrays[:len(param_names)]))
                in_arrays = all_arrays[len(param_names):]
                return self._jit_fn(p_arrays, buffers, *in_arrays,
                                    _training=training)

            tensor_args = [t if isinstance(t, Tensor) else Tensor(t)
                           for t in args]
            return apply_op("jit_program", one_op, *param_tensors,
                            *tensor_args)
        t_args = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
        return apply_op("jit_program",
                        lambda *arrs: self._jit_fn(*arrs), *t_args)

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = sf
            return fn
        if hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
            return StaticFunction(fn, input_spec, layer=fn.__self__)
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    return fn


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persist weights + input spec; the program is re-traced
    at load (source-of-truth is the Python forward, the jax idiom; the
    reference persists ProgramDesc instead)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, Layer):
        state = {k: v.numpy() for k, v in layer.state_dict().items()}
        meta = {
            "class": type(layer).__name__,
            "input_spec": [
                {"shape": s.shape, "dtype": str(s.dtype), "name": s.name}
                for s in (input_spec or [])
            ],
        }
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f)
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(meta, f)
        _LIVE_LAYERS[path] = layer
    else:
        raise TypeError("jit.save expects a Layer")


_LIVE_LAYERS = {}


class TranslatedLayer(Layer):
    def __init__(self, inner):
        super().__init__()
        self._inner = inner

    def forward(self, *args, **kwargs):
        return self._inner(*args, **kwargs)


def load(path, **configs):
    if path in _LIVE_LAYERS:
        return _LIVE_LAYERS[path]
    raise NotImplementedError(
        "jit.load across processes requires the model class to re-trace; "
        "load weights with paddle_tpu.load + Layer.set_state_dict instead.")


def enable_to_static(flag=True):
    global _to_static_enabled
    _to_static_enabled = flag


_to_static_enabled = True


def ignore_module(modules):
    pass
