"""Functionalization of stateful Layers — the hinge between paddle-shaped
eager modules and jax transforms (jit/grad/vmap/pjit).

The reference's analog is dy2static's ``partial_program``
(/root/reference/python/paddle/jit/dy2static/partial_program.py) which runs a
traced program inside dygraph. Here the direction is TPU-idiomatic: a Layer's
parameters/buffers are extracted to a pytree, and ``functional_call`` runs the
layer's Python forward with arrays swapped in — so ``jax.jit``, ``jax.grad``,
``jax.vjp`` and pjit shardings all apply directly to paddle Layers.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Tuple

import jax

from ..core import autograd
from ..core.tensor import Tensor


def state_arrays(layer) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Extract (params, buffers) as name->jax.Array dicts."""
    params = {name: p._data for name, p in layer.named_parameters()}
    buffers = {name: b._data for name, b in layer.named_buffers()
               if b is not None}
    return params, buffers


@contextlib.contextmanager
def _swapped_state(layer, params, buffers):
    named_p = dict(layer.named_parameters())
    named_b = {n: b for n, b in layer.named_buffers() if b is not None}
    old_p = {n: t._data for n, t in named_p.items()}
    old_b = {n: t._data for n, t in named_b.items()}
    try:
        for n, arr in params.items():
            if n in named_p:
                named_p[n]._data = arr
        for n, arr in buffers.items():
            if n in named_b:
                named_b[n]._data = arr
        yield
    finally:
        for n, t in named_p.items():
            t._data = old_p[n]
        for n, t in named_b.items():
            t._data = old_b[n]


def functional_call(layer, params, buffers, *args, training=None, **kwargs):
    """Run layer's forward with the given arrays; returns raw jax arrays.

    Must be called under trace (jit/grad) or eagerly; autograd recording is
    disabled since differentiation is jax's job here.
    """
    prev_training = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    try:
        with _swapped_state(layer, params, buffers), autograd.no_grad():
            t_args = [Tensor(a, stop_gradient=True) if isinstance(a, jax.Array)
                      else a for a in args]
            out = layer(*t_args, **kwargs)
        return jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))
    finally:
        layer.train() if prev_training else layer.eval()
