"""TrainStep — one fully-compiled, buffer-donated training step.

This is the TPU performance path: forward + backward + optimizer update as a
single XLA program (the analog of the reference's whole-Program execution via
InterpreterCore, but with fusion done by XLA). Eager `loss.backward();
opt.step()` keeps working for UX; TrainStep is what benchmarks and real
training loops should use.
"""
from __future__ import annotations

import functools
import operator
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..distributed import shard as shard_api
from ..distributed.mesh_utils import get_global_mesh
from ..framework import random as random_mod
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .functional import _swapped_state, state_arrays


def _norm_spec(mesh, spec):
    """Degrade axes absent from (or trivial in) the mesh to replication so
    single-chip runs are unchanged (the unified surface's normalize)."""
    return shard_api.normalize_spec(spec, mesh)


def _param_sharding(mesh, p):
    """NamedSharding for a parameter from its ``dist_spec`` annotation
    (set by the unified sharding API / TP layers / sharding stages)."""
    return NamedSharding(mesh,
                         PartitionSpec(*_norm_spec(mesh,
                                                   getattr(p, "dist_spec",
                                                           None))))


def _global_put(a, sharding):
    """device_put that also works when ``sharding`` spans processes
    (multi-host SPMD): each process contributes its addressable shards
    from the full host value via make_array_from_callback. Arrays that
    are already global stay on device (host fetch would be illegal)."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(a, sharding)
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        if a.sharding == sharding:
            return a
        return jax.device_put(a, sharding)
    arr = np.asarray(a)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _batch_axes(mesh):
    """Mesh axes the input batch dim is sharded over: dp and (ZeRO)
    sharding (the unified surface's batch_axes)."""
    return shard_api.batch_axes(mesh)


def _functional_clip(grad_clip, grads: dict) -> dict:
    if grad_clip is None:
        return grads
    if isinstance(grad_clip, ClipGradByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grads.values()))
        clip = grad_clip.clip_norm
        factor = jnp.where(gn > clip, clip / jnp.maximum(gn, 1e-12), 1.0)
        return {k: (g * factor.astype(g.dtype)) for k, g in grads.items()}
    if isinstance(grad_clip, ClipGradByNorm):
        out = {}
        for k, g in grads.items():
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            out[k] = jnp.where(n > grad_clip.clip_norm,
                               g * (grad_clip.clip_norm / n), g)
        return out
    if isinstance(grad_clip, ClipGradByValue):
        return {k: jnp.clip(g, grad_clip.min, grad_clip.max)
                for k, g in grads.items()}
    return grads


def _make_loss_of(ts):
    """The model+loss closure of a TrainStep: functional state swap, AMP
    autocast, traced dropout keys, n_inputs batch slicing. Shared by the
    plain pure step and the DGC/LocalSGD shard_map bodies so their
    semantics cannot drift."""
    import contextlib

    from ..amp.auto_cast import auto_cast
    from ..core import autograd as ag
    from ..framework import random as random_mod

    model, loss_fn = ts.model, ts.loss_fn
    amp_level, amp_dtype = ts._amp_level, ts._amp_dtype

    def loss_of(train_params, all_params, buffers, key, batch):
        full = {**all_params, **train_params}
        amp_ctx = (auto_cast(level=amp_level, dtype=amp_dtype)
                   if amp_level else contextlib.nullcontext())
        # AMP under trace: dispatch-level autocast runs inside the traced
        # forward, so XLA sees bf16 matmuls with f32 master params
        # (reference O1/O2, auto_cast.py:668) and fuses the casts away.
        with _swapped_state(model, full, buffers), ag.no_grad(), \
                random_mod.traced_key_scope(key), amp_ctx:
            t_batch = [Tensor(a, stop_gradient=True) for a in batch]
            out = model(*t_batch[:ts._n_inputs])
            loss_t = loss_fn(out, *t_batch[ts._n_inputs:])
        l_arr = loss_t._data if isinstance(loss_t, Tensor) else loss_t
        return l_arr.astype(jnp.float32)

    return loss_of


class TrainStep:
    """Compile model.forward + loss + optimizer into one donated XLA step.

    Usage::

        step = TrainStep(model, loss_fn, optimizer)   # loss_fn(out, *labels)
        loss = step(x, label)                          # one fused device step
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 donate: bool = True, amp_level: Optional[str] = None,
                 amp_dtype: str = "bfloat16", scaler=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._compiled = None
        self._donate = donate
        self._amp_level = amp_level  # None | "O1" | "O2"
        self._amp_dtype = amp_dtype
        # fp16 dynamic loss scaling fused into the compiled step: scale,
        # found_inf, skip-update branch and the incr/decr schedule are all
        # in-graph (reference: GradScaler found_inf protocol,
        # /root/reference/python/paddle/amp/grad_scaler.py:602). The python
        # GradScaler object mirrors the device state (its counters become
        # jax scalars; don't call scaler.update() yourself — the step does).
        self._scaler = scaler if (scaler is not None
                                  and scaler.is_enable()) else None
        self._scaler_state = None
        self._named_params = dict(model.named_parameters())
        self._trainable = {n: p for n, p in self._named_params.items()
                           if not p.stop_gradient}
        # persistent-compile-cache memo: arg-signature -> loaded AOT
        # executable (False = this signature failed AOT, use plain jit)
        self._exec_memo: Dict = {}
        self._step_fp: Optional[str] = None
        # xstats memo: (tag, batch-signature) -> ExecEntry so the
        # per-step dispatch note is a dict hit, not a re-registration
        self._xstats_memo: Dict = {}
        # numerics tripwires: armed state pinned at construction (the
        # in-graph grad-health reductions change the compiled program,
        # same pin contract as CachedDecoder's use_pallas)
        try:
            from ..observability import numerics as _numerics
            self._numerics_armed = (_numerics.train_tripwire_armed()
                                    and bool(self._trainable))
        except Exception:  # noqa: BLE001 - observability is garnish
            self._numerics_armed = False

    def _init_opt_state(self):
        opt = self.optimizer
        state = {}
        for name, p in self._trainable.items():
            state[name] = {an: opt._get_accum(an, p)
                           for an in opt._accum_names}
        if getattr(opt, "_localsgd_cfg", None) is not None:
            # k/last-sync/loss0/lr0 scalars of the LocalSGD schedule ride
            # the opt_state tree under a reserved key
            sc = getattr(opt, "_ls_scalars", None)
            if sc is None:
                from ..distributed.fleet.meta_parallel.dgc_localsgd import (
                    localsgd_scalar_init)
                sc = localsgd_scalar_init(opt._localsgd_cfg)
            state["__ls__"] = sc
        return state

    def _writeback_opt_state(self, state):
        opt = self.optimizer
        ls = state.get("__ls__")
        if ls is not None:
            # write through any HybridParallelOptimizer wrapper: the inner
            # optimizer owns the schedule scalars (state_dict serializes
            # them from there)
            getattr(opt, "_inner_opt", opt)._ls_scalars = ls
        for name, p in self._trainable.items():
            for an in opt._accum_names:
                opt._set_accum(an, p, state[name][an])

    def _step_fingerprint(self) -> str:
        """Identity of the compiled step WITHOUT tracing it: model class
        sources + parameter structure, loss/optimizer update-rule
        sources, clip/AMP/scaler/schedule config, the per-parameter
        constants the trace bakes in (weight decay, lr multipliers, ASP
        masks), and the sharding spec tree (dist_spec/opt_state_spec
        shape the lowered SPMD program — two spec trees must never
        share an executable). Anything that changes the lowered program
        must land here — a collision serves wrong numerics from the
        cache."""
        gen = shard_api.specs_generation()
        if self._step_fp is not None and \
                getattr(self, "_step_fp_gen", None) == gen:
            return self._step_fp
        self._step_fp_gen = gen
        from ..compile_cache import fingerprint as fpmod
        opt = self.optimizer
        parts = [
            fpmod.layer_fingerprint(self.model),
            fpmod.function_fingerprint(self.loss_fn),
            "specs:" + shard_api.spec_tree_hash(
                shard_api.model_spec_tree(self.model)),
            f"{type(opt).__module__}.{type(opt).__qualname__}",
            fpmod.function_fingerprint(opt._update_rule),
            repr(sorted(opt._accum_names)),
            repr((self._amp_level, self._amp_dtype, self._donate)),
            repr(getattr(opt, "_l2_coeff", None)),
            repr(getattr(opt, "_dgc_cfg", None)),
            repr(getattr(opt, "_localsgd_cfg", None)),
            repr(("numerics", getattr(self, "_numerics_armed", False))),
        ]
        gc = getattr(opt, "_grad_clip", None)
        parts.append(repr((type(gc).__qualname__ if gc is not None
                           else None,
                           getattr(gc, "clip_norm", None),
                           getattr(gc, "min", None),
                           getattr(gc, "max", None))))
        if self._scaler is not None:
            parts.append(repr((float(self._scaler._incr_ratio),
                               float(self._scaler._decr_ratio),
                               int(self._scaler._incr_every),
                               int(self._scaler._decr_every),
                               bool(self._scaler._dynamic))))
        for n in sorted(self._trainable):
            p = self._trainable[n]
            mult = getattr(p, "optimize_attr",
                           {"learning_rate": 1.0})["learning_rate"]
            parts.append(f"{n}:{opt._wd_for(p)}:{mult}")
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                parts.append(
                    n + ":asp:" +
                    fpmod.bytes_fingerprint(np.asarray(mask).tobytes()))
        self._step_fp = fpmod.bytes_fingerprint(
            "\n".join(parts).encode())
        return self._step_fp

    def _cached_step(self, call_args):
        """Persistent-cache tier of the step dispatch: a ready AOT
        executable for this argument signature, or None (cache
        disabled, or this signature failed AOT — the jit path always
        remains). A hit skips BOTH the Python trace and the XLA
        compile; a miss traces once via ``lower`` and persists the
        executable for the next process."""
        from ..framework.flags import flag_value, flags_generation
        if not str(flag_value("FLAGS_compile_cache_dir") or ""):
            return None
        multi = self._compiled is getattr(self, "_compiled_multi", None)
        tag = f"multi:{self._multi_n}" if multi else "single"
        leaves = jax.tree_util.tree_leaves(call_args)
        # flags_generation / specs_generation: a set_flags call (flag
        # flip / repointed cache dir) or a sharding re-annotation
        # (apply_sharding, shard_spec, mark_param) invalidates the
        # memo, never serving a stale exec for the old spec tree
        sig = (flags_generation(), shard_api.specs_generation(), tag, tuple(
            (tuple(getattr(a, "shape", ())),
             str(getattr(a, "dtype", type(a).__name__)))
            for a in leaves))
        memo = self._exec_memo
        if sig in memo:
            fn = memo[sig]
            return fn if fn is not False else None
        fn = None
        try:
            from .. import compile_cache as cc
            cache = cc.default_cache()
            if cache is not None:
                key, parts = cc.cache_key(
                    self._step_fingerprint(), list(call_args),
                    extra={"site": "train_step", "tag": tag,
                           "n_inputs": int(self._n_inputs)})
                fn, _hit = cache.get_or_compile(
                    key,
                    lambda: self._compiled.lower(*call_args).compile(),
                    site="train_step", meta=parts,
                    xstats_meta=self._xstats_meta(call_args, tag))
        except Exception:  # noqa: BLE001 - any cache/AOT failure falls
            fn = None      # back to the plain jit dispatch
        memo[sig] = fn if fn is not None else False
        return fn

    # ------------------------------------------------- xstats wiring
    @staticmethod
    def _xstats_signature(call_args, tag: str) -> tuple:
        """Registry signature of this step dispatch: the tag (single
        vs a run_steps scan window, whose executable differs at equal
        operand shapes) plus the operand shape/dtype tuple."""
        from ..observability import xstats
        return (((0,), "tag:" + tag),) + xstats.signature_of(call_args)

    def _xstats_meta(self, call_args, tag: str):
        """xstats registration payload for the persistent-cache tier:
        identity + a lower thunk the registry can use at scrape time
        when the stored tier has no Compiled to analyze."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return None
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    tuple(getattr(a, "shape", ())), a.dtype), call_args)
            compiled_ref = self._compiled
            spec_hash = None
            try:
                spec_hash = shard_api.spec_tree_hash(
                    shard_api.model_spec_tree(self.model))
            except Exception:  # noqa: BLE001 - provenance garnish
                pass
            return {"kind": "train",
                    "signature": self._xstats_signature(call_args, tag),
                    "fingerprint": self._step_fingerprint(),
                    "spec_hash": spec_hash,
                    "lower_thunk": lambda: compiled_ref.lower(*specs)}
        except Exception:  # noqa: BLE001 - never break the step path
            return None

    def _xstats_note(self, call_args, step_fn):
        """Per-step dispatch note into the xstats registry (memoized:
        steady state is one dict lookup + a counter). Cache-off runs
        register here with a lower thunk; cache-backed runs merge into
        the entry ``get_or_compile`` created."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return
            multi = self._compiled is getattr(self, "_compiled_multi",
                                              None)
            tag = f"multi:{self._multi_n}" if multi else "single"
            arrays = call_args[7:]
            memo_key = (tag, tuple(
                (tuple(getattr(a, "shape", ())),
                 str(getattr(a, "dtype", ""))) for a in arrays))
            ent = self._xstats_memo.get(memo_key)
            if ent is None:
                sig = self._xstats_signature(call_args, tag)
                if step_fn is not None:
                    # the persistent-cache tier registered this entry
                    # inside get_or_compile — merge-fetch it
                    ent = xstats.register_executable("train_step", sig)
                else:
                    meta = self._xstats_meta(call_args, tag) or {}
                    ent = xstats.register_executable(
                        "train_step", sig, kind="train",
                        fingerprint=meta.get("fingerprint"),
                        spec_hash=meta.get("spec_hash"),
                        provenance={"cache": "off"},
                        lower_thunk=meta.get("lower_thunk"))
                if ent is None:
                    return
                self._xstats_memo[memo_key] = ent
            xstats.note_dispatch(ent)
        except Exception:  # noqa: BLE001 - observability is garnish on
            pass           # the hot path, never a step failure

    def _numerics_note(self, num_stats, new_sc):
        """Hand the step's device health scalars ([grad_norm,
        grad_finite_fraction, loss_is_finite]) to the numerics layer.
        Sampled on the host; the layer defers the actual device read
        by one step, so this never syncs the step that produced them."""
        try:
            from ..observability import numerics
            if not numerics.sample_decision(numerics.tripwire_rate()):
                return
            scale = new_sc.get("scale") if isinstance(new_sc, dict) \
                else None
            numerics.note_train_step(num_stats, loss_scale=scale)
        except Exception:  # noqa: BLE001 - observability is garnish on
            pass           # the hot path, never a step failure

    def _make_pure_step(self):
        """Dispatch to the step-structure builder: the plain GSPMD step,
        or the DGC / LocalSGD communication-reducing variants when the
        fleet strategy swapped in an optimizer carrying their config."""
        opt = self.optimizer
        if getattr(opt, "_dgc_cfg", None) is not None:
            from ..distributed.fleet.meta_parallel.dgc_localsgd import (
                build_dgc_pure_step)
            return build_dgc_pure_step(self)
        if getattr(opt, "_localsgd_cfg", None) is not None:
            from ..distributed.fleet.meta_parallel.dgc_localsgd import (
                build_localsgd_pure_step)
            return build_localsgd_pure_step(self)
        return self._make_pure_step_plain()

    def _make_pure_step_plain(self):
        """Construct the pure (params, buffers, opt_state, sc_state, lr, t,
        key, *batch) -> (loss, params', opt_state', sc_state') function.
        Shared by the jit path (_build) and the AOT planning path
        (aot_lower), which traces it with abstract operands only."""
        opt = self.optimizer
        loss_closure = _make_loss_of(self)
        trainable_names = list(self._trainable.keys())
        grad_clip = getattr(opt, "_grad_clip", None)
        update_rule = opt._update_rule
        wd_by_name = {n: opt._wd_for(p) for n, p in self._trainable.items()}
        lr_mult = {n: getattr(p, "optimize_attr", {"learning_rate": 1.0})[
            "learning_rate"] for n, p in self._trainable.items()}

        # ASP n:m sparsity masks (incubate.asp.prune_model attaches them):
        # re-applied in-graph after every update so the compiled path keeps
        # the sparsity guarantee the eager decorated optimizer provides
        asp_masks = {n: jnp.asarray(p._asp_mask)
                     for n, p in self._trainable.items()
                     if getattr(p, "_asp_mask", None) is not None}
        scaler = self._scaler
        numerics_armed = getattr(self, "_numerics_armed", False)
        if scaler is not None:
            sc_cfg = dict(incr_ratio=float(scaler._incr_ratio),
                          decr_ratio=float(scaler._decr_ratio),
                          incr_every=int(scaler._incr_every),
                          decr_every=int(scaler._decr_every),
                          dynamic=bool(scaler._dynamic))

        def pure_step(params, buffers, opt_state, sc_state, lr, t, key,
                      *batch):
            def loss_of(train_params):
                return loss_closure(train_params, params, buffers, key,
                                    batch)

            train_params = {n: params[n] for n in trainable_names}
            if scaler is not None:
                scale = sc_state["scale"]
                loss_s, grads = jax.value_and_grad(
                    lambda tp: loss_of(tp) * scale)(train_params)
                loss = loss_s / scale
                inv = (1.0 / scale)
                grads = {k: (g.astype(jnp.float32) * inv).astype(g.dtype)
                         for k, g in grads.items()}
                found_inf = functools.reduce(
                    jnp.logical_or,
                    [jnp.any(~jnp.isfinite(g.astype(jnp.float32)))
                     for g in grads.values()])
            else:
                loss, grads = jax.value_and_grad(loss_of)(train_params)
                found_inf = None
            num_stats = None
            if numerics_armed and grads:
                # numerics tripwires: fixed-shape grad-health
                # reductions fused into the step ([grad_norm,
                # grad_finite_fraction, loss_is_finite] — the host
                # read is deferred by the numerics layer, never here)
                total_el = float(sum(
                    int(np.prod(g.shape)) for g in grads.values()) or 1)
                finite_ct = functools.reduce(
                    operator.add,
                    [jnp.sum(jnp.isfinite(g).astype(jnp.float32))
                     for g in grads.values()])
                sq = functools.reduce(
                    operator.add,
                    [jnp.sum(jnp.square(jnp.where(
                        jnp.isfinite(g), g, 0).astype(jnp.float32)))
                     for g in grads.values()])
                num_stats = jnp.stack(
                    [jnp.sqrt(sq), finite_ct / total_el,
                     jnp.isfinite(loss).astype(jnp.float32)])
            # Pin each grad to its param's shard layout IMMEDIATELY: with
            # ZeRO ('sharding'/dist specs) XLA otherwise defers the
            # reduce-scatters and keeps full unsharded f32 grads live for
            # many layers at once (measured ~15 GB/chip of temp on the
            # ERNIE-10B v5e-64 plan, seq-independent). The constraint makes
            # each layer's grad scatter as soon as it is produced.
            mesh_now = get_global_mesh()
            if mesh_now is not None:
                for n in list(grads.keys()):
                    p_obj = self._trainable[n]
                    spec = getattr(p_obj, "opt_state_spec", None)
                    if spec is None:
                        spec = getattr(p_obj, "dist_spec", None)
                    if spec is None:
                        continue
                    norm = _norm_spec(mesh_now, spec)
                    if any(a is not None for a in norm):
                        grads[n] = jax.lax.with_sharding_constraint(
                            grads[n],
                            NamedSharding(mesh_now, PartitionSpec(*norm)))
            grads = _functional_clip(grad_clip, grads)
            new_params = dict(params)
            new_state = {}
            for n in trainable_names:
                g = grads[n]
                p_arr = params[n]
                if g.dtype != p_arr.dtype:
                    g = g.astype(p_arr.dtype)
                if opt._l2_coeff and not opt._decoupled_wd():
                    g = g + opt._l2_coeff * p_arr
                p_new, s_new = update_rule(
                    p_arr, g, lr * lr_mult[n], t,
                    jnp.asarray(wd_by_name[n], jnp.float32), opt_state[n])
                if n in asp_masks:
                    p_new = p_new * asp_masks[n].astype(p_new.dtype)
                if found_inf is not None:
                    # skip-update branch: overflowed steps leave params and
                    # optimizer accumulators untouched
                    p_new = jnp.where(found_inf, p_arr, p_new)
                    s_new = {an: jnp.where(found_inf, opt_state[n][an], v)
                             for an, v in s_new.items()}
                new_params[n] = p_new
                new_state[n] = s_new
            if scaler is None:
                # optimization_barrier: numerically-identical outputs (e.g.
                # both Adam moments of a zero-grad param) must NOT be CSE'd
                # into one buffer — the next call feeds outputs back as
                # DONATED inputs, and XLA rejects donating a buffer twice
                if num_stats is not None:
                    # health scalars ride a reserved sc_state key;
                    # _call_inner pops it back out before reseeding so
                    # the next call's operand structure is unchanged
                    out_sc = dict(sc_state, numerics=num_stats)
                    loss, new_params, new_state, out_sc = \
                        jax.lax.optimization_barrier(
                            (loss, new_params, new_state, out_sc))
                    return loss, new_params, new_state, out_sc
                loss, new_params, new_state = jax.lax.optimization_barrier(
                    (loss, new_params, new_state))
                return loss, new_params, new_state, sc_state
            # dynamic loss-scale schedule, in-graph
            good, bad = sc_state["good"], sc_state["bad"]
            if sc_cfg["dynamic"]:
                good = jnp.where(found_inf, 0, good + 1)
                bad = jnp.where(found_inf, bad + 1, 0)
                dec = bad >= sc_cfg["decr_every"]
                inc = good >= sc_cfg["incr_every"]
                scale = jnp.where(
                    dec, jnp.maximum(scale * sc_cfg["decr_ratio"], 1.0),
                    scale)
                scale = jnp.where(inc, scale * sc_cfg["incr_ratio"], scale)
                bad = jnp.where(dec, 0, bad)
                good = jnp.where(inc, 0, good)
            new_sc = {"scale": scale, "good": good, "bad": bad,
                      "found_inf": found_inf}
            if num_stats is not None:
                new_sc["numerics"] = num_stats
            loss, new_params, new_state, new_sc = \
                jax.lax.optimization_barrier(
                    (loss, new_params, new_state, new_sc))
            return loss, new_params, new_state, new_sc

        return pure_step

    def _build(self):
        pure_step = self._make_pure_step()
        donate = (0, 2) if self._donate else ()
        self._pure_step = pure_step
        mesh = get_global_mesh()
        if mesh is None:
            self._compiled = jax.jit(pure_step, donate_argnums=donate)
            self._mesh = None
        else:
            # SPMD path: params/opt-state laid out by dist_spec, batch
            # sharded over the dp (+ZeRO sharding) axes; XLA/GSPMD inserts
            # the collectives the reference's Reducer/c_ops did by hand
            # (SURVEY §2.3 TPU-native equivalent row).
            self._mesh = mesh
            p_sh = {n: _param_sharding(mesh, p)
                    for n, p in self._named_params.items()}
            repl = NamedSharding(mesh, PartitionSpec())
            opt_sh = {}
            # DGC u/v and LocalSGD per-rank params/accums are stacked
            # (D, *shape) with the rank dim sharded over 'dp'
            dp_stacked = (
                (getattr(self.optimizer, "_dgc_cfg", None) is not None
                 or getattr(self.optimizer, "_localsgd_cfg", None)
                 is not None)
                and "dp" in mesh.axis_names and mesh.shape["dp"] > 1)
            for n, p in self._trainable.items():
                per = {}
                # ZeRO stage-1/2: optimizer state shards over the
                # 'sharding' axis even when the param itself is replicated
                # (GroupShardedStage2 sets p.opt_state_spec)
                os_spec = getattr(p, "opt_state_spec", None)
                if os_spec is not None:
                    state_sh = NamedSharding(
                        mesh, PartitionSpec(*_norm_spec(mesh, os_spec)))
                else:
                    state_sh = p_sh[n]
                for an in self.optimizer._accum_names:
                    acc = self.optimizer._get_accum(an, p)
                    if dp_stacked and getattr(acc, "ndim", 0) == \
                            len(p.shape) + 1:
                        per[an] = NamedSharding(mesh, PartitionSpec("dp"))
                    else:
                        per[an] = state_sh if getattr(acc, "ndim", 0) == \
                            len(p.shape) and len(p.shape) > 0 else repl
                opt_sh[n] = per
            if getattr(self.optimizer, "_localsgd_cfg", None) is not None:
                opt_sh["__ls__"] = {k: repl
                                    for k in ("k", "last", "loss0", "lr0")}

            baxes = _batch_axes(mesh)
            bspec = PartitionSpec(baxes if baxes else None)
            self._batch_sharding = NamedSharding(mesh, bspec)
            self._param_shardings = p_sh
            self._opt_shardings = opt_sh
            self._repl = repl
            # Shardings are applied by committed placement (device_put) in
            # __call__; jit then compiles one SPMD program over the mesh.
            self._compiled = jax.jit(pure_step, donate_argnums=donate)

    def run_steps(self, n_steps: int, *batch,
                  n_inputs: Optional[int] = None):
        """Run ``n_steps`` training steps on the SAME batch inside ONE
        compiled program (``lax.scan`` over the step body). This is the
        dispatch-amortized path: per-call host/runtime overhead is paid
        once for the whole window instead of per step — the analog of the
        reference executing a multi-iteration Program in one
        InterpreterCore run. Dropout keys advance per step (fold_in);
        the LR is held for the window. Returns the final step's loss.
        """
        self._n_inputs = n_inputs if n_inputs is not None else \
            getattr(self, "_n_inputs", len(batch) - 1)
        if self._compiled is None:
            self._build()
        if getattr(self, "_compiled_multi", None) is None or \
                self._multi_n != n_steps:
            ps = self._pure_step
            self._multi_n = n_steps

            has_num = {"seen": False}   # set at body trace time

            def multi(params, buffers, opt_state, sc_state, lr, t0, key,
                      *batch):
                def body(carry, i):
                    params, opt_state, sc_state = carry
                    k = jax.random.fold_in(key, i)
                    loss, p2, s2, sc2 = ps(params, buffers, opt_state,
                                           sc_state, lr, t0 + i, k, *batch)
                    # the step ADDS found_inf (and, when the tripwires
                    # are armed, numerics) to the scaler state; keep
                    # the carry structure fixed and thread them as
                    # outputs
                    fi = sc2.get("found_inf", jnp.zeros((), jnp.bool_)) \
                        if sc2 else jnp.zeros((), jnp.bool_)
                    nm = sc2.get("numerics") if sc2 else None
                    if nm is not None:
                        has_num["seen"] = True
                    else:
                        nm = jnp.zeros((3,), jnp.float32)
                    sc_carry = {k2: v for k2, v in sc2.items()
                                if k2 not in ("found_inf", "numerics")}
                    return (p2, s2, sc_carry), (loss, fi, nm)

                (p, s, sc), (losses, fis, nums) = jax.lax.scan(
                    body, (params, opt_state, sc_state),
                    jnp.arange(n_steps, dtype=jnp.int32))
                if sc:
                    sc = dict(sc, found_inf=fis[-1])
                if has_num["seen"]:
                    sc = dict(sc, numerics=nums[-1])
                return losses[-1], p, s, sc

            self._compiled_multi = jax.jit(
                multi, donate_argnums=(0, 2) if self._donate else ())
        saved = self._compiled
        self._compiled = self._compiled_multi
        try:
            out = self.__call__(*batch, n_inputs=self._n_inputs)
        finally:
            self._compiled = saved
        self.optimizer._step_count += n_steps - 1
        return out

    def aot_lower(self, mesh, *batch, n_inputs: Optional[int] = None,
                  compiler_options: Optional[dict] = None):
        """AOT-compile ONE training step over ``mesh`` from abstract
        operands only — nothing is materialized, so it composes with
        ``paddle.LazyGuard`` models whose parameters are ShapeDtypeStructs
        (the ERNIE-10B-on-v5e-64 memory plan in ``__graft_entry__``).

        ``mesh`` may be built from ``jax.experimental.topologies`` — an AOT
        TPU topology with no attached chips — in which case the returned
        ``jax.stages.Compiled`` carries the real XLA-TPU per-chip memory
        plan (``.memory_analysis()``) and FLOP estimate
        (``.cost_analysis()``) for the sharded step. ``batch`` entries may
        be ShapeDtypeStructs or example arrays.
        """
        self._n_inputs = n_inputs if n_inputs is not None else \
            max(len(batch) - 1, 1)
        if getattr(self.optimizer, "_dgc_cfg", None) is not None or \
                getattr(self.optimizer, "_localsgd_cfg", None) is not None:
            raise NotImplementedError(
                "aot_lower plans the plain GSPMD step; DGC/LocalSGD "
                "schedules are not supported there")
        pure_step = self._make_pure_step_plain()
        repl = NamedSharding(mesh, PartitionSpec())

        def sds(shape, dtype, sh):
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh)

        p_sh = {n: _param_sharding(mesh, p)
                for n, p in self._named_params.items()}
        params_abs = {n: sds(p.shape, p._data.dtype, p_sh[n])
                      for n, p in self._named_params.items()}
        buffers_abs = {n: sds(b.shape, b._data.dtype, repl)
                       for n, b in self.model.named_buffers()
                       if b is not None}
        opt = self.optimizer
        opt_abs = {}
        for n, p in self._trainable.items():
            os_spec = getattr(p, "opt_state_spec", None)
            if os_spec is not None:
                state_sh = NamedSharding(
                    mesh, PartitionSpec(*_norm_spec(mesh, os_spec)))
            else:
                state_sh = p_sh[n]
            per = {}
            for an in opt._accum_names:
                shape, dtype = opt._accum_spec(an, p)
                full = len(shape) == len(p.shape) and len(p.shape) > 0
                per[an] = sds(shape, dtype, state_sh if full else repl)
            opt_abs[n] = per
        # a throwaway key for shape/dtype only — do NOT draw from the global
        # stream (planning must have no side effect on training randomness)
        key = jax.random.key(0)
        baxes = _batch_axes(mesh)
        bsh = NamedSharding(mesh, PartitionSpec(baxes if baxes else None))
        batch_abs = []
        for b in batch:
            if isinstance(b, jax.ShapeDtypeStruct):
                batch_abs.append(
                    b if b.sharding is not None
                    else sds(b.shape, b.dtype, bsh))
            else:
                arr = b._data if isinstance(b, Tensor) else Tensor(b)._data
                sh = bsh if getattr(arr, "ndim", 0) >= 1 else repl
                batch_abs.append(sds(arr.shape, arr.dtype, sh))
        sc_abs = {}
        if self._scaler is not None:
            sc_abs = {"scale": sds((), jnp.float32, repl),
                      "good": sds((), jnp.int32, repl),
                      "bad": sds((), jnp.int32, repl)}
        lowered = jax.jit(
            pure_step,
            donate_argnums=(0, 2) if self._donate else ()).lower(
            params_abs, buffers_abs, opt_abs, sc_abs,
            sds((), jnp.float32, repl), sds((), jnp.int32, repl),
            sds(key.shape, key.dtype, repl), *batch_abs)
        return lowered.compile(compiler_options)

    def __call__(self, *batch, n_inputs: Optional[int] = None):
        """batch = model inputs followed by loss_fn extra args (labels).

        The call runs inside a goodput ``step`` frame (compile events
        fired by jax.monitoring during a first-call trace claim their
        seconds out of the frame, so step vs compile attribution is
        exact) and drops one envelope into the continuous step
        profiler — stragglers become error spans in the flight
        recorder."""
        from ..observability.goodput import default_ledger
        from ..observability.stepprof import default_profiler
        ledger = default_ledger()
        ledger.begin("step")
        try:
            out = self._call_inner(*batch, n_inputs=n_inputs)
        finally:
            wall_s = ledger.end()
            try:
                default_profiler().record_step(
                    wall_s * 1e3, kind="train",
                    step=int(self.optimizer._step_count))
            except Exception:  # noqa: BLE001 - profiling is garnish on
                pass           # the hot path, never a step failure
        return out

    def _call_inner(self, *batch, n_inputs: Optional[int] = None):
        self._n_inputs = n_inputs if n_inputs is not None else \
            getattr(self, "_n_inputs", len(batch) - 1)
        if self._compiled is None:
            self._build()
        params, buffers = state_arrays(self.model)
        opt_state = self._init_opt_state()
        if getattr(self, "_mesh", None) is not None:
            # Keep state device-resident across steps: arrays we placed (or
            # produced) last step are already laid out per dist_spec — skip
            # the per-step device_put round-trip (VERDICT r1 weak #4) and
            # only re-place entries the user swapped out between steps.
            # The cache holds strong refs (source, placed) so `is` identity
            # is sound (no dead-id reuse).
            cache = getattr(self, "_place_cache", None)
            if cache is None:
                cache = self._place_cache = {}

            def place(key, a, sharding):
                hit = cache.get(key)
                if hit is not None and hit[0] is a:
                    return hit[1]
                placed = _global_put(a, sharding)
                cache[key] = (a, placed)
                return placed

            params = {n: place(("p", n), a, self._param_shardings[n])
                      for n, a in params.items()}
            buffers = {n: place(("b", n), a, self._repl)
                       for n, a in buffers.items()}
            opt_state = {
                n: {an: place(("s", n, an), a, self._opt_shardings[n][an])
                    for an, a in per.items()}
                for n, per in opt_state.items()}
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.optimizer._step_count, jnp.int32)
        key = random_mod.next_key()
        if self._scaler is not None:
            epoch = getattr(self._scaler, "_epoch", 0)
            if self._scaler_state is None or \
                    getattr(self, "_scaler_epoch", None) != epoch:
                # (re)seed from the python GradScaler — including after a
                # load_state_dict (checkpoint resume bumps _epoch)
                self._scaler_epoch = epoch
                self._scaler_state = {
                    "scale": jnp.asarray(float(self._scaler._scale),
                                         jnp.float32),
                    "good": jnp.asarray(int(self._scaler._good_steps),
                                        jnp.int32),
                    "bad": jnp.asarray(int(self._scaler._bad_steps),
                                       jnp.int32),
                }
            sc_state = dict(self._scaler_state)
            sc_state.pop("found_inf", None)
            sc_state.pop("numerics", None)
        else:
            sc_state = {}
        # paddle dtype defaulting (python floats → default float dtype), not
        # jnp.asarray's — which under x64 would yield f64/i64 inputs
        arrays = [b._data if isinstance(b, Tensor) else Tensor(b)._data
                  for b in batch]
        if getattr(self, "_mesh", None) is not None:
            nshards = int(np.prod([self._mesh.shape[a]
                                   for a in _batch_axes(self._mesh)] or [1]))
            arrays = [_global_put(a, self._batch_sharding)
                      if getattr(a, "ndim", 0) >= 1
                      and a.shape[0] % nshards == 0 else a
                      for a in arrays]
        call_args = (params, buffers, opt_state, sc_state, lr, t, key,
                     *arrays)
        step_fn = self._cached_step(call_args)
        loss, new_params, new_state, new_sc = \
            (step_fn if step_fn is not None else self._compiled)(*call_args)
        self._xstats_note(call_args, step_fn)
        num_stats = None
        if isinstance(new_sc, dict) and "numerics" in new_sc:
            # strip the reserved tripwire key so the scaler mirror and
            # the next step's reseeded operands keep their structure
            new_sc = dict(new_sc)
            num_stats = new_sc.pop("numerics")
        if not getattr(loss, "is_fully_addressable", True):
            # multi-host mesh: the scalar loss is replicated; hand back the
            # process-local copy so .numpy()/float() work on every rank
            loss = jnp.asarray(loss.addressable_shards[0].data)
        for n, p in self._named_params.items():
            p._data = new_params[n]
        self._writeback_opt_state(new_state)
        if self._scaler is not None:
            self._scaler_state = new_sc
            # mirror device state into the python GradScaler (lazy: these
            # stay jax scalars until someone reads state_dict / get_*)
            self._scaler._scale = new_sc["scale"]
            self._scaler._good_steps = new_sc["good"]
            self._scaler._bad_steps = new_sc["bad"]
            self._scaler._found_inf = new_sc["found_inf"]
        if num_stats is not None:
            self._numerics_note(num_stats, new_sc)
        if getattr(self, "_mesh", None) is not None:
            # outputs are already correctly sharded; next step reuses them
            # without re-placement (their old donated inputs are dropped)
            cache = self._place_cache
            for n, a in new_params.items():
                cache[("p", n)] = (a, a)
            for n, per in new_state.items():
                for an, a in per.items():
                    cache[("s", n, an)] = (a, a)
        if isinstance(self.optimizer._lr, object) and hasattr(
                self.optimizer._lr, "step") and not isinstance(
                self.optimizer._lr, (int, float)):
            pass  # LR scheduler stepping is the caller's choice (paddle API)
        return Tensor(loss)
