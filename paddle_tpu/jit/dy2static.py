"""dy2static AST fallback: tensor-dependent Python control flow → lax.

The reference converts dygraph code with ~20 AST transformers
(/root/reference/python/paddle/jit/dy2static/, e.g. ifelse_transformer.py
rewriting ``if``/``else`` into cond(), while_transformer.py into while_loop
ops). TPU-native version: ``to_static`` traces first (the fast path — most
models have no tensor-dependent branching); when tracing raises
``TracerBoolConversionError``, the function source is AST-rewritten so that

    if <pred>: A            →  def __t(): A; return outs
    else: B                    def __f(): B; return outs
                               outs = _dy2s_cond(<pred>, __t, __f)

    while <pred>: body      →  carry = _dy2s_while(cond_fn, body_fn, carry)

and re-traced. The runtime helpers dispatch dynamically: a concrete
(python/eager) predicate takes the plain Python branch, a traced Tensor
predicate lowers through ``static.nn.cond`` / ``static.nn.while_loop``
(→ ``lax.cond`` / ``lax.while_loop``), so the SAME rewritten function runs
eagerly and compiled — the dy2static contract.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable

__all__ = ["ast_transform", "convert_call_guard", "_dy2s_cond",
           "_dy2s_while"]


class _Undefined:
    """Sentinel for a name not bound on the taken path (the reference's
    dy2static UndefinedVar). Binding it is harmless; USING it raises
    UnboundLocalError (a NameError subclass — `except NameError` handlers
    written against the original code keep working) with a message that
    names the actual problem."""

    __slots__ = ()

    def __repr__(self):
        return "<dy2static undefined>"

    def _fail(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: variable is not defined on every control-flow "
            "path that reaches this use (assign it in both branches / "
            "before the loop)")

    __bool__ = __float__ = __int__ = __len__ = __iter__ = _fail
    __add__ = __radd__ = __sub__ = __rsub__ = _fail
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _fail
    __floordiv__ = __rfloordiv__ = __mod__ = __rmod__ = _fail
    __pow__ = __rpow__ = __and__ = __or__ = __xor__ = _fail
    __matmul__ = __rmatmul__ = __getitem__ = __call__ = _fail
    __lt__ = __le__ = __gt__ = __ge__ = _fail
    # == / != would otherwise silently fall back to identity comparison —
    # the one place silent wrongness is worst
    __eq__ = __ne__ = _fail
    __hash__ = None  # eq without hash: keep it out of dicts/sets quietly
    __neg__ = __pos__ = __abs__ = __array__ = _fail

    def __getattr__(self, name):
        self._fail()


_UNDEF = _Undefined()


def _dy2s_get(thunk):
    """Evaluate a name capture; unbound names become the _UNDEF sentinel
    so rewriting extra (concrete) branches never introduces NameErrors
    the original code didn't have."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return _UNDEF


def _is_traced(x):
    import jax

    from ..core.tensor import Tensor
    return isinstance(x, jax.core.Tracer) or (
        isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer))


def _dy2s_cond(pred, true_fn, false_fn):
    """Runtime dispatch for a rewritten ``if``: python branch when the
    predicate is concrete, ``static.nn.cond`` when traced."""
    if not _is_traced(pred):
        import numpy as np

        from ..core.tensor import Tensor
        p = pred._data if isinstance(pred, Tensor) else pred
        return true_fn() if bool(np.asarray(p).item()) else false_fn()
    from ..static import nn as static_nn
    return static_nn.cond(pred, true_fn, false_fn)


def _dy2s_while(cond_fn, body_fn, carry):
    """Runtime dispatch for a rewritten ``while`` over a tuple carry."""
    probe = cond_fn(*carry)
    if not _is_traced(probe) and not any(_is_traced(c) for c in carry):
        while True:
            import numpy as np

            from ..core.tensor import Tensor
            p = probe._data if isinstance(probe, Tensor) else probe
            if not bool(np.asarray(p).item()):
                return tuple(carry)
            carry = tuple(body_fn(*carry))
            probe = cond_fn(*carry)
    from ..static import nn as static_nn
    out = static_nn.while_loop(cond_fn, body_fn, list(carry))
    return tuple(out)


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (no descent into nested defs)."""

    def __init__(self):
        self.names = set()
        self.unsupported = None

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # the def binds its name; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Return(self, node):
        self.unsupported = "return"

    def visit_Break(self, node):
        self.unsupported = "break"

    def visit_Continue(self, node):
        self.unsupported = "continue"

    def visit_Global(self, node):
        self.unsupported = "global"

    def visit_Yield(self, node):
        self.unsupported = "yield"


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names, v.unsupported


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrite if/while statements into _dy2s_cond/_dy2s_while calls.

    Conservative: statements whose bodies contain constructs the lowering
    cannot represent (return/break/continue/global/yield) are left as
    plain Python — they keep working for concrete predicates and raise
    the original tracer error for traced ones.
    """

    _uid = 0

    @classmethod
    def _fresh(cls, stem):
        cls._uid += 1
        return f"__dy2s_{stem}_{cls._uid}"

    # -- if/else → cond ----------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_names, bad1 = _assigned(node.body)
        else_names, bad2 = _assigned(node.orelse)
        if bad1 or bad2:
            return node
        outs = sorted(body_names | else_names)
        t_name = self._fresh("true")
        f_name = self._fresh("false")
        # branch-assigned names become PARAMETERS defaulted to their
        # enclosing-scope values (defaults evaluate at def time, i.e.
        # right before the cond): this pre-binds read-modify-write
        # locals (`out = out + x` inside the branch) and names the other
        # branch never assigns, with _dy2s_get turning genuinely unbound
        # ones into a sentinel instead of a NameError.
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in outs],
            ctx=ast.Load()))
        # evaluate the TEST first (a side-effecting test — e.g. a walrus
        # binding one of the outs — must run before the branch defs
        # snapshot enclosing values via their parameter defaults)
        p_name = self._fresh("pred")
        pred_stmt = ast.Assign(
            targets=[ast.Name(id=p_name, ctx=ast.Store())],
            value=node.test)
        true_def = _make_fn(t_name, _defaulted_args(outs),
                            list(node.body) + [ret])
        false_body = list(node.orelse) if node.orelse else []
        false_def = _make_fn(f_name, _defaulted_args(outs),
                             false_body + [_copy_ret(ret)])
        call = ast.Call(
            func=ast.Name(id="_dy2s_cond", ctx=ast.Load()),
            args=[ast.Name(id=p_name, ctx=ast.Load()),
                  ast.Name(id=t_name, ctx=ast.Load()),
                  ast.Name(id=f_name, ctx=ast.Load())],
            keywords=[])
        if outs:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in outs],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [pred_stmt, true_def, false_def, assign]

    # -- while → while_loop ------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node
        carry_names, bad = _assigned(node.body)
        if bad:
            return node
        carry = sorted(carry_names)
        if not carry:
            return node
        c_name = self._fresh("cond")
        b_name = self._fresh("body")
        cond_def = _make_fn(c_name, _named_args(carry),
                            [ast.Return(value=node.test)])
        body_ret = ast.Return(value=ast.Tuple(
            elts=[_capture(n) for n in carry],
            ctx=ast.Load()))
        body_def = _make_fn(b_name, _named_args(carry),
                            list(node.body) + [body_ret])
        call = ast.Call(
            func=ast.Name(id="_dy2s_while", ctx=ast.Load()),
            args=[ast.Name(id=c_name, ctx=ast.Load()),
                  ast.Name(id=b_name, ctx=ast.Load()),
                  ast.Tuple(elts=[_capture(n) for n in carry],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carry],
                ctx=ast.Store())],
            value=call)
        return [cond_def, body_def, assign]


def _capture(n):
    """``_dy2s_get(lambda: n)`` — a late-bound, NameError-safe read of an
    enclosing-scope variable (see _dy2s_get)."""
    return ast.Call(
        func=ast.Name(id="_dy2s_get", ctx=ast.Load()),
        args=[ast.Lambda(args=_empty_args(),
                         body=ast.Name(id=n, ctx=ast.Load()))],
        keywords=[])


def _defaulted_args(names):
    """Parameters ``(n=_dy2s_get(lambda: n), ...)`` pre-bound from the
    enclosing scope (defaults evaluate at def time, in that scope)."""
    a = _named_args(names)
    a.defaults = [_capture(n) for n in names]
    return a


def _make_fn(name, args, body):
    fn = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[], returns=None,
                         type_comment=None)
    if "type_params" in ast.FunctionDef._fields:  # py3.12+
        fn.type_params = []
    return fn


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _named_args(names):
    return ast.arguments(posonlyargs=[],
                         args=[ast.arg(arg=n) for n in names],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def _copy_ret(ret):
    import copy
    return copy.deepcopy(ret)


@functools.lru_cache(maxsize=256)
def _transform_code(src_key, filename):
    tree = ast.parse(src_key)
    fn_def = tree.body[0]
    fn_def.decorator_list = []  # don't re-apply to_static on exec
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    return compile(new_tree, filename or "<dy2static>", "exec")


def ast_transform(fn: Callable) -> Callable:
    """Return ``fn`` with tensor-dependent if/while rewritten to lax-able
    control flow. Raises OSError if the source is unavailable (REPL)."""
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        fn = fn.__func__
    src = textwrap.dedent(inspect.getsource(fn))
    code = _transform_code(src, inspect.getsourcefile(fn))
    glb = dict(fn.__globals__)
    glb["_dy2s_cond"] = _dy2s_cond
    glb["_dy2s_while"] = _dy2s_while
    glb["_dy2s_get"] = _dy2s_get
    # rebuild the closure environment as globals (the re-exec'd def has no
    # closure cells; free variables become module-level lookups)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:  # empty cell (self-reference)
                pass
    loc = {}
    exec(code, glb, loc)
    new_fn = loc[fn.__name__]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__dy2static_transformed__ = True
    if bound_self is not None:
        return functools.partial(new_fn, bound_self)
    return new_fn


def convert_call_guard(e: BaseException) -> bool:
    """True when a tracing failure is the tensor-dependent-control-flow
    kind the AST fallback can fix. TracerArrayConversionError is included
    because Tensor.__bool__ reaches the tracer via .numpy() (``if t:``
    surfaces as an array conversion, not a bool conversion)."""
    import jax

    return isinstance(e, (jax.errors.TracerBoolConversionError,
                          jax.errors.TracerArrayConversionError,
                          jax.errors.ConcretizationTypeError))
