"""dy2static AST fallback: tensor-dependent Python control flow → lax.

The reference converts dygraph code with ~20 AST transformers
(/root/reference/python/paddle/jit/dy2static/, e.g. ifelse_transformer.py
rewriting ``if``/``else`` into cond(), while_transformer.py into while_loop
ops). TPU-native version: ``to_static`` traces first (the fast path — most
models have no tensor-dependent branching); when tracing raises
``TracerBoolConversionError``, the function source is AST-rewritten so that

    if <pred>: A            →  def __t(): A; return outs
    else: B                    def __f(): B; return outs
                               outs = _dy2s_cond(<pred>, __t, __f)

    while <pred>: body      →  carry = _dy2s_while(cond_fn, body_fn, carry)

and re-traced. The runtime helpers dispatch dynamically: a concrete
(python/eager) predicate takes the plain Python branch, a traced Tensor
predicate lowers through ``static.nn.cond`` / ``static.nn.while_loop``
(→ ``lax.cond`` / ``lax.while_loop``), so the SAME rewritten function runs
eagerly and compiled — the dy2static contract.

Statement coverage (the reference's dedicated transformers):
- early ``return`` → return-flag + value slot + guarded trailing code
  (return_transformer.py analog, ``_ReturnTransformer``)
- ``break``/``continue`` → loop flags + guarded trailing statements +
  augmented loop test (break_continue_transformer.py analog)
- ``for i in range(<tensor>)`` → counter while-loop
- nested tensor-dependent if/while compose (inner rewrites are re-created
  inside the outer branch functions, never carried through cond)

Known limit: reverse-mode autograd through a TRACED while (dynamic trip
count) is unsupported by XLA/jax (lax.while_loop has no transpose rule);
converted loops serve forward/inference, and gradient flows through every
converted ``if``. ``return`` inside a loop body stays plain Python.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable

__all__ = ["ast_transform", "convert_call_guard", "_dy2s_cond",
           "_dy2s_while", "_dy2s_not", "_dy2s_and"]


class _Undefined:
    """Sentinel for a name not bound on the taken path (the reference's
    dy2static UndefinedVar). Binding it is harmless; USING it raises
    UnboundLocalError (a NameError subclass — `except NameError` handlers
    written against the original code keep working) with a message that
    names the actual problem."""

    __slots__ = ()

    def __repr__(self):
        return "<dy2static undefined>"

    def _fail(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: variable is not defined on every control-flow "
            "path that reaches this use (assign it in both branches / "
            "before the loop)")

    __bool__ = __float__ = __int__ = __len__ = __iter__ = _fail
    __add__ = __radd__ = __sub__ = __rsub__ = _fail
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _fail
    __floordiv__ = __rfloordiv__ = __mod__ = __rmod__ = _fail
    __pow__ = __rpow__ = __and__ = __or__ = __xor__ = _fail
    __matmul__ = __rmatmul__ = __getitem__ = __call__ = _fail
    __lt__ = __le__ = __gt__ = __ge__ = _fail
    # == / != would otherwise silently fall back to identity comparison —
    # the one place silent wrongness is worst
    __eq__ = __ne__ = _fail
    __hash__ = None  # eq without hash: keep it out of dicts/sets quietly
    __neg__ = __pos__ = __abs__ = __array__ = _fail

    def __getattr__(self, name):
        self._fail()


_UNDEF = _Undefined()


def _dy2s_get(thunk):
    """Evaluate a name capture; unbound names become the _UNDEF sentinel
    so rewriting extra (concrete) branches never introduces NameErrors
    the original code didn't have."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return _UNDEF


def _is_traced(x):
    import jax

    from ..core.tensor import Tensor
    return isinstance(x, jax.core.Tracer) or (
        isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer))


def _dy2s_not(x):
    """``not x`` that stays traced for Tensor/tracer operands (plain
    ``not`` would force __bool__ and kill the trace)."""
    if _is_traced(x):
        from ..tensor.logic import logical_not
        return logical_not(x)
    import numpy as np

    from ..core.tensor import Tensor
    p = x._data if isinstance(x, Tensor) else x
    return not bool(np.asarray(p).item())


def _dy2s_int(v):
    """range()-argument semantics for the for→while rewrite: concrete
    values must be integers (float args raise TypeError exactly like
    ``range`` would — the rewrite must not silently run a loop eager
    Python rejects); traced values pass through, requiring an integer
    dtype."""
    if _is_traced(v):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        arr = v._data if isinstance(v, Tensor) else v
        if not jnp.issubdtype(arr.dtype, jnp.integer):
            raise TypeError(
                f"'{arr.dtype}' tensor cannot be interpreted as an "
                f"integer (range bound)")
        return v
    import operator

    import numpy as np

    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        arr = np.asarray(v._data)
        if not np.issubdtype(arr.dtype, np.integer):
            raise TypeError(
                f"'{arr.dtype}' tensor cannot be interpreted as an "
                f"integer (range bound)")
        return int(arr.item())
    return operator.index(v)


def _dy2s_and(a, b_thunk):
    """Short-circuit ``a and b()`` for concrete ``a``; logical_and of both
    for traced (loop-guard composition: the rewritten test is pure)."""
    if not _is_traced(a):
        import numpy as np

        from ..core.tensor import Tensor
        p = a._data if isinstance(a, Tensor) else a
        if not bool(np.asarray(p).item()):
            return False
        return b_thunk()
    from ..tensor.logic import logical_and
    return logical_and(a, b_thunk())


def _dy2s_cond(pred, true_fn, false_fn, names=None):
    """Runtime dispatch for a rewritten ``if``: python branch when the
    predicate is concrete, ``static.nn.cond`` when traced.

    Traced predicates with a value bound on only ONE path (the other
    side yields the _UNDEF sentinel) fall back to
    compute-both-and-select. Internal early-return slots (``__dy2s_*``
    names) borrow the defined side's value as a placeholder — correct
    because the return-flag discipline guards every later use; a USER
    variable bound on only one path raises UnboundLocalError (using it
    after a traced if would be undefined behavior)."""
    if not _is_traced(pred):
        import numpy as np

        from ..core.tensor import Tensor
        p = pred._data if isinstance(pred, Tensor) else pred
        return true_fn() if bool(np.asarray(p).item()) else false_fn()
    from ..static import nn as static_nn
    try:
        return static_nn.cond(pred, true_fn, false_fn)
    except (TypeError, ValueError, UnboundLocalError):
        # UnboundLocalError: the cond wrapper touched a _UNDEF sentinel
        # structurally (e.g. unwrapping ._data); a GENUINE use-before-
        # assign still raises from inside true_fn()/false_fn() below
        t_out = true_fn()
        f_out = false_fn()
        single = not isinstance(t_out, tuple)
        if single:
            t_out, f_out = (t_out,), (f_out,)
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        def pick(i, t, f):
            # None = the rewrite's initial value for a not-yet-bound slot
            t_undef = isinstance(t, _Undefined) or t is None
            f_undef = isinstance(f, _Undefined) or f is None
            if t_undef and f_undef:
                return t
            if t_undef or f_undef:
                name = names[i] if names and i < len(names) else ""
                if not str(name).startswith("__dy2s_"):
                    # user name bound on one path only: bind the sentinel
                    # — harmless if never read again (e.g. a local inside
                    # a return-guard block), honest UnboundLocalError at
                    # the first later USE
                    return _UNDEF
                return f if t_undef else t
            if isinstance(t, Tensor) or isinstance(f, Tensor):
                # route through the DISPATCHED where so the autograd tape
                # records the select: gradient flows through the surviving
                # branch (the docstring's contract) instead of being cut
                # by a raw stop_gradient Tensor wrap
                from ..tensor.search import where as _where
                tt = t if isinstance(t, Tensor) else Tensor(t)
                ff = f if isinstance(f, Tensor) else Tensor(f)
                pr = pred if isinstance(pred, Tensor) else Tensor(pred)
                return _where(pr, tt, ff)
            return jnp.where(pred._data if isinstance(pred, Tensor)
                             else pred, t, f)
        outs = tuple(pick(i, t, f)
                     for i, (t, f) in enumerate(zip(t_out, f_out)))
        return outs[0] if single else outs


def _dy2s_while(cond_fn, body_fn, carry):
    """Runtime dispatch for a rewritten ``while`` over a tuple carry."""
    probe = cond_fn(*carry)
    if not _is_traced(probe) and not any(_is_traced(c) for c in carry):
        while True:
            import numpy as np

            from ..core.tensor import Tensor
            p = probe._data if isinstance(probe, Tensor) else probe
            if not bool(np.asarray(p).item()):
                return tuple(carry)
            carry = tuple(body_fn(*carry))
            probe = cond_fn(*carry)
    if any(isinstance(c, _Undefined) for c in carry):
        # a loop-local name (e.g. the for-range induction var) has no
        # value before the loop; one abstract body pass discovers the
        # slot's type so it can enter lax.while_loop as a placeholder.
        # A body that USES the slot before assigning trips the sentinel
        # (honest use-before-bind error).
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        out = body_fn(*carry)
        patched = []
        for c, o in zip(carry, out):
            if isinstance(c, _Undefined):
                if isinstance(o, Tensor):
                    c = Tensor(jnp.zeros_like(o._data),
                               stop_gradient=True)
                elif isinstance(o, _Undefined):
                    pass  # never assigned either: keep the sentinel
                else:
                    c = jnp.zeros_like(jnp.asarray(o))
            patched.append(c)
        carry = tuple(patched)
    from ..static import nn as static_nn
    out = static_nn.while_loop(cond_fn, body_fn, list(carry))
    return tuple(out)


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (no descent into nested defs)."""

    def __init__(self):
        self.names = set()
        self.unsupported = None

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # the def binds its name; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Return(self, node):
        self.unsupported = "return"

    def visit_Break(self, node):
        self.unsupported = "break"

    def visit_Continue(self, node):
        self.unsupported = "continue"

    def visit_Global(self, node):
        self.unsupported = "global"

    def visit_Yield(self, node):
        self.unsupported = "yield"


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names, v.unsupported


def _contains(stmts, kinds, stop_at_loops=False):
    """Any node of the given ast types in the statement list (not
    descending into nested function defs; optionally not into loops)."""
    found = []

    class V(ast.NodeVisitor):
        def generic_visit(self, n):
            if isinstance(n, kinds):
                found.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return
            if stop_at_loops and isinstance(n, (ast.While, ast.For)):
                return
            super().generic_visit(n)
    for s in stmts:
        V().visit(s)
    return bool(found)


def _not_call(name):
    return ast.Call(func=ast.Name(id="_dy2s_not", ctx=ast.Load()),
                    args=[ast.Name(id=name, ctx=ast.Load())],
                    keywords=[])


def _bool_const(v):
    return ast.Constant(value=v)


def _assign_name(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value)


class _ReturnTransformer:
    """Early-return flattening (the reference's return_transformer.py):
    every ``return X`` becomes ``__dy2s_rflag, __dy2s_rval = True, X``,
    statements after a maybe-returning ``if`` are guarded behind
    ``if _dy2s_not(__dy2s_rflag)``, and the function ends with
    ``return __dy2s_rval``. Returns inside loops are not representable
    this way — functions containing them are left untouched."""

    FLAG = "__dy2s_rflag"
    VAL = "__dy2s_rval"

    def apply(self, fn_def):
        has_nested_return = _contains(
            fn_def.body, (ast.Return,)) and any(
            isinstance(s, (ast.If, ast.While, ast.For)) and
            _contains([s], (ast.Return,)) for s in fn_def.body)
        if not has_nested_return:
            return fn_def
        # returns inside loops can't be expressed with a flag alone
        for s in ast.walk(fn_def):
            if isinstance(s, (ast.While, ast.For)) and \
                    _contains(s.body + s.orelse, (ast.Return,)):
                return fn_def
        body, _may = self._rewrite(fn_def.body)
        fn_def.body = [
            _assign_name(self.FLAG, _bool_const(False)),
            _assign_name(self.VAL, ast.Constant(value=None)),
        ] + body + [ast.Return(value=ast.Name(id=self.VAL,
                                              ctx=ast.Load()))]
        return fn_def

    def _rewrite(self, stmts):
        out = []
        may_return = False
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(ast.Assign(
                    targets=[ast.Tuple(
                        elts=[ast.Name(id=self.FLAG, ctx=ast.Store()),
                              ast.Name(id=self.VAL, ctx=ast.Store())],
                        ctx=ast.Store())],
                    value=ast.Tuple(
                        elts=[_bool_const(True),
                              s.value or ast.Constant(value=None)],
                        ctx=ast.Load())))
                return out, True  # rest of the block is unreachable
            if isinstance(s, ast.If) and _contains([s], (ast.Return,)):
                s.body, r1 = self._rewrite(s.body)
                s.orelse, r2 = self._rewrite(s.orelse)
                out.append(s)
                if r1 or r2:
                    may_return = True
                    rest, r3 = self._rewrite(stmts[i + 1:])
                    if rest:
                        out.append(ast.If(test=_not_call(self.FLAG),
                                          body=rest, orelse=[]))
                    return out, True
                continue
            out.append(s)
        return out, may_return


class _BreakContinueRewriter:
    """break/continue flattening for one loop body (the reference's
    break_continue_transformer.py): ``break``/``continue`` set a flag,
    trailing statements are guarded, and the loop test gains
    ``and not break_flag``."""

    def __init__(self, brk_name, cont_name):
        self.brk = brk_name
        self.cont = cont_name
        self.used_brk = False
        self.used_cont = False

    def _guard(self):
        flags = []
        if self.used_brk:
            flags.append(self.brk)
        if self.used_cont:
            flags.append(self.cont)
        test = _not_call(flags[0])
        for f in flags[1:]:
            test = ast.BoolOp(op=ast.And(),
                              values=[test, _not_call(f)])
        return test

    def rewrite(self, stmts):
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                self.used_brk = True
                out.append(_assign_name(self.brk, _bool_const(True)))
                return out, True
            if isinstance(s, ast.Continue):
                self.used_cont = True
                out.append(_assign_name(self.cont, _bool_const(True)))
                return out, True
            if isinstance(s, ast.If) and _contains(
                    [s], (ast.Break, ast.Continue), stop_at_loops=True):
                s.body, e1 = self.rewrite(s.body)
                s.orelse, e2 = self.rewrite(s.orelse)
                out.append(s)
                if e1 or e2:
                    rest, _ = self.rewrite(stmts[i + 1:])
                    if rest:
                        out.append(ast.If(test=self._guard(), body=rest,
                                          orelse=[]))
                    return out, True
                continue
            out.append(s)
        return out, False


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrite if/while/for statements into _dy2s_cond/_dy2s_while calls.

    Conservative: statements whose bodies contain constructs the lowering
    cannot represent after the return/break/continue flattening passes
    (return-in-loop, global, yield) are left as plain Python — they keep
    working for concrete predicates and raise the original tracer error
    for traced ones.
    """

    _uid = 0

    @classmethod
    def _fresh(cls, stem):
        cls._uid += 1
        return f"__dy2s_{stem}_{cls._uid}"

    # -- if/else → cond ----------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_names, bad1 = _assigned(node.body)
        else_names, bad2 = _assigned(node.orelse)
        if bad1 or bad2:
            return node
        # transformer-generated defs/preds are re-created inside the
        # branch functions on every trace; carrying the function objects
        # through cond would hand lax non-array leaves
        outs = sorted(n for n in (body_names | else_names)
                      if not n.startswith("__dy2s_")
                      or n in (_ReturnTransformer.FLAG,
                               _ReturnTransformer.VAL)
                      or n.startswith("__dy2s_brk")
                      or n.startswith("__dy2s_cont")
                      or n.startswith("__dy2s_it"))
        t_name = self._fresh("true")
        f_name = self._fresh("false")
        # branch-assigned names become PARAMETERS defaulted to their
        # enclosing-scope values (defaults evaluate at def time, i.e.
        # right before the cond): this pre-binds read-modify-write
        # locals (`out = out + x` inside the branch) and names the other
        # branch never assigns, with _dy2s_get turning genuinely unbound
        # ones into a sentinel instead of a NameError.
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in outs],
            ctx=ast.Load()))
        # evaluate the TEST first (a side-effecting test — e.g. a walrus
        # binding one of the outs — must run before the branch defs
        # snapshot enclosing values via their parameter defaults)
        p_name = self._fresh("pred")
        pred_stmt = ast.Assign(
            targets=[ast.Name(id=p_name, ctx=ast.Store())],
            value=node.test)
        true_def = _make_fn(t_name, _defaulted_args(outs),
                            list(node.body) + [ret])
        false_body = list(node.orelse) if node.orelse else []
        false_def = _make_fn(f_name, _defaulted_args(outs),
                             false_body + [_copy_ret(ret)])
        call = ast.Call(
            func=ast.Name(id="_dy2s_cond", ctx=ast.Load()),
            args=[ast.Name(id=p_name, ctx=ast.Load()),
                  ast.Name(id=t_name, ctx=ast.Load()),
                  ast.Name(id=f_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in outs],
                            ctx=ast.Load())],
            keywords=[])
        if outs:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in outs],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [pred_stmt, true_def, false_def, assign]

    # -- while → while_loop ------------------------------------------------
    def visit_While(self, node):
        # break/continue flattening BEFORE conversion (flags + guards);
        # must run before generic_visit so nested ifs convert the guarded
        # form
        if _contains(node.body, (ast.Break, ast.Continue),
                     stop_at_loops=True) and not node.orelse:
            brk = self._fresh("brk")
            cont = self._fresh("cont")
            rw = _BreakContinueRewriter(brk, cont)
            new_body, _ = rw.rewrite(node.body)
            if rw.used_cont:
                # continue: per-iteration flag, reset at body start
                new_body = [_assign_name(cont, _bool_const(False))] + \
                    new_body
            pre = []
            if rw.used_cont:
                pre.append(_assign_name(cont, _bool_const(False)))
            if rw.used_brk:
                pre.append(_assign_name(brk, _bool_const(False)))
                # test := not brk and <orig test> (lazy rhs)
                node.test = ast.Call(
                    func=ast.Name(id="_dy2s_and", ctx=ast.Load()),
                    args=[_not_call(brk),
                          ast.Lambda(args=_empty_args(), body=node.test)],
                    keywords=[])
            node.body = new_body
            out = self.visit_While(node)
            return pre + (out if isinstance(out, list) else [out])
        self.generic_visit(node)
        if node.orelse:
            return node
        carry_names, bad = _assigned(node.body)
        if bad:
            return node
        carry = sorted(n for n in carry_names
                       if not n.startswith("__dy2s_")
                       or n.startswith("__dy2s_brk")
                       or n.startswith("__dy2s_cont")
                       or n.startswith("__dy2s_it")
                       or n in (_ReturnTransformer.FLAG,
                                _ReturnTransformer.VAL))
        if not carry:
            return node
        c_name = self._fresh("cond")
        b_name = self._fresh("body")
        cond_def = _make_fn(c_name, _named_args(carry),
                            [ast.Return(value=node.test)])
        body_ret = ast.Return(value=ast.Tuple(
            elts=[_capture(n) for n in carry],
            ctx=ast.Load()))
        body_def = _make_fn(b_name, _named_args(carry),
                            list(node.body) + [body_ret])
        call = ast.Call(
            func=ast.Name(id="_dy2s_while", ctx=ast.Load()),
            args=[ast.Name(id=c_name, ctx=ast.Load()),
                  ast.Name(id=b_name, ctx=ast.Load()),
                  ast.Tuple(elts=[_capture(n) for n in carry],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carry],
                ctx=ast.Store())],
            value=call)
        return [cond_def, body_def, assign]

    # -- for over range() → while -----------------------------------------
    def visit_For(self, node):
        """``for i in range(...)`` (the tensor-bounded loop idiom) becomes
        an explicit counter while-loop, then converts through
        visit_While. Other iterables stay plain Python."""
        if node.orelse or not isinstance(node.target, ast.Name):
            self.generic_visit(node)
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            self.generic_visit(node)
            return node
        args = it.args
        start = args[0] if len(args) >= 2 else ast.Constant(value=0)
        stop = args[1] if len(args) >= 2 else args[0]
        step = args[2] if len(args) == 3 else ast.Constant(value=1)
        if isinstance(step, ast.Constant) and isinstance(step.value, int):
            if step.value == 0:
                self.generic_visit(node)
                return node
            cmp_op = ast.Lt() if step.value > 0 else ast.Gt()
        else:
            # unknown step sign: not statically expressible
            self.generic_visit(node)
            return node
        counter = self._fresh("it")
        stop_n = self._fresh("it_stop")

        def _as_int(expr):
            return ast.Call(func=ast.Name(id="_dy2s_int", ctx=ast.Load()),
                            args=[expr], keywords=[])
        pre = [_assign_name(counter, _as_int(start)),
               _assign_name(stop_n, _as_int(stop))]
        test = ast.Compare(left=ast.Name(id=counter, ctx=ast.Load()),
                           ops=[cmp_op],
                           comparators=[ast.Name(id=stop_n,
                                                 ctx=ast.Load())])
        # increment BEFORE the user body: a `continue` inside the body
        # (whose guard wraps everything after it) must still advance the
        # counter, or the loop spins forever
        body = [_assign_name(node.target.id,
                             ast.Name(id=counter, ctx=ast.Load())),
                _assign_name(counter, ast.BinOp(
                    left=ast.Name(id=counter, ctx=ast.Load()),
                    op=ast.Add(), right=step))] + list(node.body)
        wh = ast.While(test=test, body=body, orelse=[])
        out = self.visit_While(wh)
        return pre + (out if isinstance(out, list) else [out])


def _capture(n):
    """``_dy2s_get(lambda: n)`` — a late-bound, NameError-safe read of an
    enclosing-scope variable (see _dy2s_get)."""
    return ast.Call(
        func=ast.Name(id="_dy2s_get", ctx=ast.Load()),
        args=[ast.Lambda(args=_empty_args(),
                         body=ast.Name(id=n, ctx=ast.Load()))],
        keywords=[])


def _defaulted_args(names):
    """Parameters ``(n=_dy2s_get(lambda: n), ...)`` pre-bound from the
    enclosing scope (defaults evaluate at def time, in that scope)."""
    a = _named_args(names)
    a.defaults = [_capture(n) for n in names]
    return a


def _make_fn(name, args, body):
    fn = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[], returns=None,
                         type_comment=None)
    if "type_params" in ast.FunctionDef._fields:  # py3.12+
        fn.type_params = []
    return fn


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _named_args(names):
    return ast.arguments(posonlyargs=[],
                         args=[ast.arg(arg=n) for n in names],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def _copy_ret(ret):
    import copy
    return copy.deepcopy(ret)


@functools.lru_cache(maxsize=256)
def _transform_code(src_key, filename):
    tree = ast.parse(src_key)
    fn_def = tree.body[0]
    fn_def.decorator_list = []  # don't re-apply to_static on exec
    _ReturnTransformer().apply(fn_def)  # before control-flow conversion
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    return compile(new_tree, filename or "<dy2static>", "exec")


def ast_transform(fn: Callable) -> Callable:
    """Return ``fn`` with tensor-dependent if/while rewritten to lax-able
    control flow. Raises OSError if the source is unavailable (REPL)."""
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        fn = fn.__func__
    src = textwrap.dedent(inspect.getsource(fn))
    code = _transform_code(src, inspect.getsourcefile(fn))
    glb = dict(fn.__globals__)
    glb["_dy2s_cond"] = _dy2s_cond
    glb["_dy2s_while"] = _dy2s_while
    glb["_dy2s_get"] = _dy2s_get
    glb["_dy2s_not"] = _dy2s_not
    glb["_dy2s_and"] = _dy2s_and
    glb["_dy2s_int"] = _dy2s_int
    # rebuild the closure environment as globals (the re-exec'd def has no
    # closure cells; free variables become module-level lookups)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:  # empty cell (self-reference)
                pass
    loc = {}
    exec(code, glb, loc)
    new_fn = loc[fn.__name__]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__dy2static_transformed__ = True
    if bound_self is not None:
        return functools.partial(new_fn, bound_self)
    return new_fn


def convert_call_guard(e: BaseException) -> bool:
    """True when a tracing failure is the tensor-dependent-control-flow
    kind the AST fallback can fix. TracerArrayConversionError is included
    because Tensor.__bool__ reaches the tracer via .numpy() (``if t:``
    surfaces as an array conversion, not a bool conversion); the TypeError
    is ``range(<traced Tensor>)`` (for-over-tensor-range)."""
    import jax

    if isinstance(e, TypeError) and \
            "cannot be interpreted as an integer" in str(e):
        return True
    return isinstance(e, (jax.errors.TracerBoolConversionError,
                          jax.errors.TracerArrayConversionError,
                          jax.errors.ConcretizationTypeError))
