from .api import (  # noqa: F401
    StaticFunction, TranslatedLayer, enable_to_static, ignore_module, load,
    not_to_static, save, to_static,
)
from .functional import functional_call, state_arrays  # noqa: F401
from .train_step import TrainStep  # noqa: F401

_verbosity = 0
_code_level = 0


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static transcription log verbosity (reference
    jit/dy2static/logging_utils.py set_verbosity)."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """How much transformed code dy2static prints (reference
    logging_utils.set_code_level)."""
    global _code_level
    _code_level = int(level)
