from .api import (  # noqa: F401
    StaticFunction, TranslatedLayer, enable_to_static, ignore_module, load,
    not_to_static, save, to_static,
)
from .functional import functional_call, state_arrays  # noqa: F401
from .train_step import TrainStep  # noqa: F401
