"""Minimal vision transforms (reference: python/paddle/vision/transforms/)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        try:
            import jax
            import jax.numpy as jnp
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            target = ((arr.shape[0],) + tuple(self.size)) if chw else \
                (tuple(self.size) + (arr.shape[-1],) if arr.ndim == 3
                 else tuple(self.size))
            return np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32),
                                               target, "bilinear"))
        except Exception:
            return arr


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.flip(np.asarray(img), axis=-1))
        return img


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:] if arr.ndim == 3 and arr.shape[0] in (1, 3) \
            else arr.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[0] in (1, 3):
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]
