"""Vision transforms — full reference surface
(python/paddle/vision/transforms/{transforms.py,functional.py}): 22
transform classes + the functional ops they build on. Images are numpy
arrays (HWC or CHW; uint8 or float) or PIL Images (converted on entry);
geometric warps use scipy.ndimage inverse mapping.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Normalize", "Transpose",
    "Resize", "RandomResizedCrop", "CenterCrop", "RandomCrop", "Pad",
    "RandomHorizontalFlip", "RandomVerticalFlip", "RandomRotation",
    "RandomAffine", "RandomPerspective", "RandomErasing", "Grayscale",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "ColorJitter",
    "to_tensor", "normalize", "resize", "crop", "center_crop", "pad",
    "hflip", "vflip", "rotate", "affine", "perspective", "erase",
    "adjust_brightness", "adjust_contrast", "adjust_hue",
    "adjust_saturation", "to_grayscale",
]


def _np_img(img):
    """PIL/ndarray -> ndarray, remembering nothing (HWC or HW)."""
    return np.asarray(img)


def _is_chw(arr):
    return arr.ndim == 3 and arr.shape[0] in (1, 3, 4) and \
        arr.shape[-1] not in (1, 3, 4)


def _to_hwc(arr):
    if _is_chw(arr):
        return arr.transpose(1, 2, 0), True
    return arr, False


def _from_hwc(arr, was_chw):
    return arr.transpose(2, 0, 1) if was_chw else arr


# ------------------------------------------------------------- functional

def to_tensor(pic, data_format="CHW"):
    src = np.asarray(pic)
    arr = src.astype(np.float32)
    if src.dtype == np.uint8:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]          # HW -> HW1, channel-last
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    import paddle_tpu as paddle
    return paddle.to_tensor(np.ascontiguousarray(arr))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    if to_rgb:
        # reference semantics: input is BGR, flip the channel axis first
        arr = arr[::-1].copy() if data_format == "CHW" \
            else arr[..., ::-1].copy()
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    if isinstance(size, numbers.Number):
        h, w = hwc.shape[:2]
        if h <= w:
            size = (int(size), max(1, int(size * w / h)))
        else:
            size = (max(1, int(size * h / w)), int(size))
    import jax
    import jax.numpy as jnp
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic", "linear": "linear"}.get(
        interpolation, "linear")
    target = tuple(size) + ((hwc.shape[-1],) if hwc.ndim == 3 else ())
    out = np.asarray(jax.image.resize(
        jnp.asarray(hwc, jnp.float32), target, method))
    if arr.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return _from_hwc(out, was_chw)


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    if _is_chw(arr):
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = np.asarray(img)
    hwc, _ = _to_hwc(arr)
    h, w = hwc.shape[:2]
    th, tw = output_size
    if th > h or tw > w:
        raise ValueError(
            f"center_crop size ({th}, {tw}) exceeds image ({h}, {w})")
    return crop(arr, (h - th) // 2, (w - tw) // 2, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    if _is_chw(arr):
        spec = [(0, 0), (pt, pb), (pl, pr)]
    elif arr.ndim == 3:
        spec = [(pt, pb), (pl, pr), (0, 0)]
    else:
        spec = [(pt, pb), (pl, pr)]
    return np.pad(arr, spec, mode=mode, **kw)


def hflip(img):
    arr = np.asarray(img)
    return np.ascontiguousarray(np.flip(arr, -1 if _is_chw(arr) else 1))


def vflip(img):
    arr = np.asarray(img)
    return np.ascontiguousarray(np.flip(arr, -2 if _is_chw(arr) else 0))


_INTERP_ORDER = {"nearest": 0, "bilinear": 1, "linear": 1, "bicubic": 3}


def _warp(hwc, matrix, fill=0.0, interpolation="bilinear",
          out_shape=None):
    """Inverse-warp an HWC image by a 3x3 homography (output->input)."""
    from scipy import ndimage
    h, w = (out_shape if out_shape is not None else hwc.shape[:2])
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xx)
    coords = np.stack([xx, yy, ones], 0).reshape(3, -1).astype(np.float64)
    src = matrix @ coords
    src = src[:2] / np.maximum(src[2:3], 1e-12)
    sx, sy = src[0].reshape(h, w), src[1].reshape(h, w)
    # epsilon-tolerant bounds: 1e-15 rotation-matrix noise must not push
    # on-grid samples "outside" (map_coordinates fills ANY coord < 0
    # with cval); genuinely-outside pixels still get the fill value
    ih, iw = hwc.shape[:2]
    eps = 1e-6
    valid = ((sx >= -eps) & (sx <= iw - 1 + eps)
             & (sy >= -eps) & (sy <= ih - 1 + eps))
    sx = np.clip(sx, 0, iw - 1)
    sy = np.clip(sy, 0, ih - 1)
    order = _INTERP_ORDER.get(interpolation, 1)
    chans = hwc[..., None] if hwc.ndim == 2 else hwc
    out = np.stack([
        np.where(valid,
                 ndimage.map_coordinates(chans[..., c].astype(np.float64),
                                         [sy, sx], order=order),
                 float(fill))
        for c in range(chans.shape[-1])], -1)
    if hwc.ndim == 2:
        out = out[..., 0]
    if np.asarray(hwc).dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out.astype(hwc.dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) T(translate); invert for warp
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1.0]]) * 1.0
    m[:2, :] *= scale
    m[0, 2] = cx + tx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + ty - m[1, 0] * cx - m[1, 1] * cy
    return np.linalg.inv(m)


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    h, w = hwc.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    ctr = center if center is not None else ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, translate, scale, shear, ctr)
    return _from_hwc(_warp(hwc, m, fill, interpolation), was_chw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    if not expand:
        return affine(img, angle, (0, 0), 1.0, (0.0, 0.0), interpolation,
                      fill, center)
    # expand: enlarge the canvas to hold the whole rotated image
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    h, w = hwc.shape[:2]
    rot = np.deg2rad(angle)
    nw = int(np.ceil(abs(w * np.cos(rot)) + abs(h * np.sin(rot))))
    nh = int(np.ceil(abs(h * np.cos(rot)) + abs(w * np.sin(rot))))
    ctr_in = ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, (0, 0), 1.0, (0.0, 0.0), ctr_in)
    # shift output coords so the new canvas center maps to the old one
    shift = np.eye(3)
    shift[0, 2] = (w - nw) * 0.5
    shift[1, 2] = (h - nh) * 0.5
    out = _warp(hwc, m @ shift, fill, interpolation, out_shape=(nh, nw))
    return _from_hwc(out, was_chw)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Warp so that startpoints map to endpoints (both 4x[x, y])."""
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    # solve the homography endpoints -> startpoints (inverse mapping)
    A, bv = [], []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bv += [sx, sy]
    sol = np.linalg.lstsq(np.asarray(A, np.float64),
                          np.asarray(bv, np.float64), rcond=None)[0]
    m = np.append(sol, 1.0).reshape(3, 3)
    return _from_hwc(_warp(hwc, m, fill, interpolation), was_chw)


def erase(img, i, j, h, w, v, inplace=False):
    arr = np.asarray(img) if inplace else np.array(img)
    if _is_chw(arr):
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img)
    out = arr.astype(np.float32) * brightness_factor
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    if hwc.ndim == 2:
        g = hwc.astype(np.float32)
    else:
        g = hwc[..., 0] * 0.299 + hwc[..., 1] * 0.587 + hwc[..., 2] * 0.114
    g = np.repeat(g[..., None], num_output_channels, -1)
    if arr.dtype == np.uint8:
        g = np.clip(np.round(g), 0, 255).astype(np.uint8)
    else:
        g = g.astype(arr.dtype)
    return _from_hwc(g, was_chw)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    gray_mean = float(np.mean(to_grayscale(hwc).astype(np.float32)))
    out = hwc.astype(np.float32) * contrast_factor + \
        gray_mean * (1 - contrast_factor)
    if arr.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    return _from_hwc(out, was_chw)


def _adjust_saturation(img, saturation_factor):
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    g = to_grayscale(hwc, 3).astype(np.float32)
    out = hwc.astype(np.float32) * saturation_factor + \
        g * (1 - saturation_factor)
    if arr.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    return _from_hwc(out, was_chw)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img)
    hwc, was_chw = _to_hwc(arr)
    f = hwc.astype(np.float32) / (255.0 if arr.dtype == np.uint8 else 1.0)
    mx = f.max(-1)
    mn = f.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    hch = np.where(mx == r, (g - b) / diff % 6,
                   np.where(mx == g, (b - r) / diff + 2,
                            (r - g) / diff + 4)) / 6.0
    hch = (hch + hue_factor) % 1.0
    s = np.where(mx > 0, diff / np.maximum(mx, 1e-12), 0.0)
    v = mx
    i = np.floor(hch * 6).astype(np.int32) % 6
    fr = hch * 6 - np.floor(hch * 6)
    p = v * (1 - s)
    q = v * (1 - fr * s)
    tt = v * (1 - (1 - fr) * s)
    out = np.select(
        [(i == 0)[..., None], (i == 1)[..., None], (i == 2)[..., None],
         (i == 3)[..., None], (i == 4)[..., None], (i == 5)[..., None]],
        [np.stack([v, tt, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, tt], -1), np.stack([p, q, v], -1),
         np.stack([tt, p, v], -1), np.stack([v, p, q], -1)])
    if arr.dtype == np.uint8:
        out = np.clip(np.round(out * 255.0), 0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    return _from_hwc(out, was_chw)


# ------------------------------------------------------------ transforms

class BaseTransform:
    """reference transforms.py BaseTransform:147: keys route dict/tuple
    inputs; subclasses implement _apply_image (and friends)."""

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)

    def _get_params(self, inputs):
        return None

    def _first_image(self, inputs):
        if isinstance(inputs, (list, tuple)):
            for k, x in zip(self.keys, inputs):
                if k == "image":
                    return x
            return inputs[0]
        return inputs

    def __call__(self, inputs):
        self.params = self._get_params(inputs)
        if isinstance(inputs, (list, tuple)):
            out = [self._apply_image(x) if k == "image" else x
                   for k, x in zip(self.keys, inputs)]
            # elements beyond keys pass through untouched (reference
            # BaseTransform semantics — labels must not be dropped)
            out.extend(inputs[len(self.keys):])
            return type(inputs)(out)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if np.asarray(img).dtype == np.uint8:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _get_params(self, inputs):
        # fractional position: resolved per image AFTER padding, but the
        # random draw is shared across paired "image" keys
        return np.random.rand(), np.random.rand()

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        hwc, _ = _to_hwc(arr)
        h, w = hwc.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, (max(tw - w, 0), max(th - h, 0)), self.fill,
                      self.padding_mode)
            hwc, _ = _to_hwc(np.asarray(arr))
            h, w = hwc.shape[:2]
        fi, fj = self.params
        i = int(fi * (h - th + 1))
        j = int(fj * (w - tw + 1))
        return crop(arr, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _get_params(self, inputs):
        hwc, _ = _to_hwc(np.asarray(self._first_image(inputs)))
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                return i, j, ch, cw
        return None

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.params is None:
            hwc, _ = _to_hwc(arr)
            return resize(center_crop(arr, min(hwc.shape[:2])),
                          self.size, self.interpolation)
        i, j, ch, cw = self.params
        return resize(crop(arr, i, j, ch, cw), self.size,
                      self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        super().__init__(keys)
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _get_params(self, inputs):
        # drawn ONCE per call so paired "image" keys flip together
        return np.random.rand() < self.prob

    def _apply_image(self, img):
        return hflip(img) if self.params else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _get_params(self, inputs):
        return np.random.rand() < self.prob

    def _apply_image(self, img):
        return vflip(img) if self.params else img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation, self.expand = interpolation, expand
        self.center, self.fill = center, fill

    def _get_params(self, inputs):
        return np.random.uniform(*self.degrees)

    def _apply_image(self, img):
        return rotate(img, self.params, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees, self.translate = degrees, translate
        self.scale_rng, self.shear_rng = scale, shear
        self.interpolation, self.fill = interpolation, fill
        self.center = center

    def _get_params(self, inputs):
        hwc, _ = _to_hwc(np.asarray(self._first_image(inputs)))
        h, w = hwc.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (0.0, 0.0)
        if self.shear_rng is not None:
            srng = self.shear_rng
            if isinstance(srng, numbers.Number):
                srng = (-abs(srng), abs(srng))
            sh = (np.random.uniform(srng[0], srng[1]), 0.0)
        return angle, (tx, ty), sc, sh

    def _apply_image(self, img):
        angle, translate, sc, sh = self.params
        return affine(img, angle, translate, sc, sh, self.interpolation,
                      self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _get_params(self, inputs):
        if np.random.rand() >= self.prob:
            return None
        hwc, _ = _to_hwc(np.asarray(self._first_image(inputs)))
        h, w = hwc.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1))]
        return start, end

    def _apply_image(self, img):
        if self.params is None:
            return img
        start, end = self.params
        return perspective(img, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _get_params(self, inputs):
        if np.random.rand() >= self.prob:
            return None
        hwc, _ = _to_hwc(np.asarray(self._first_image(inputs)))
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return i, j, eh, ew
        return None

    def _apply_image(self, img):
        if self.params is None:
            return img
        i, j, eh, ew = self.params
        return erase(np.asarray(img), i, j, eh, ew, self.value,
                     self.inplace)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _get_params(self, inputs):
        if self.value == 0:
            return None
        return np.random.uniform(max(0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        if self.params is None:
            return img
        return adjust_brightness(img, self.params)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = value

    def _get_params(self, inputs):
        if self.value == 0:
            return None
        return np.random.uniform(max(0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        if self.params is None:
            return img
        return adjust_contrast(img, self.params)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _get_params(self, inputs):
        if self.value == 0:
            return None
        return np.random.uniform(max(0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        if self.params is None:
            return img
        return _adjust_saturation(img, self.params)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _get_params(self, inputs):
        if self.value == 0:
            return None
        return np.random.uniform(-self.value, self.value)

    def _apply_image(self, img):
        if self.params is None:
            return img
        return adjust_hue(img, self.params)


class ColorJitter(BaseTransform):
    """reference ColorJitter: brightness/contrast/saturation/hue in
    random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _get_params(self, inputs):
        order = np.random.permutation(len(self.ts))
        for t in self.ts:
            t.params = t._get_params(inputs)
        return order

    def _apply_image(self, img):
        for i in self.params:
            img = self.ts[i]._apply_image(img)
        return img


# reference exposes adjust_saturation under this name too
adjust_saturation = _adjust_saturation
