"""paddle.vision.datasets — dataset parsers + hermetic synthetic stand-ins.

Reference: python/paddle/vision/datasets/{mnist,cifar,folder,flowers,
voc2012}.py. The reference downloads archives on first use; this
environment has no egress, so every real dataset class takes explicit
local file paths (``data_file=``/``image_path=``...) and raises a clear
error when they are absent, while FakeMNIST/FakeImageNet generate
deterministic data with the right shapes so pipelines and benchmarks run
hermetically. File-format parsing (idx, cifar pickle, VOC tar layout,
image folders) matches the reference loaders.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset
from ..utils.download import require_local_file as _require

__all__ = [
    "FakeMNIST", "FakeImageNet", "MNIST", "FashionMNIST", "Cifar10",
    "Cifar100", "DatasetFolder", "ImageFolder", "Flowers", "VOC2012",
]


class FakeMNIST(Dataset):
    def __init__(self, mode="train", n=1024, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(n, 1, 28, 28).astype(np.float32)
        self.labels = rng.randint(0, 10, (n, 1)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FakeImageNet(Dataset):
    def __init__(self, n=256, image_size=224, num_classes=1000, seed=0,
                 transform=None):
        self.n = n
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed
        self.transform = transform

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(3, self.image_size, self.image_size).astype(np.float32)
        label = np.asarray([rng.randint(0, self.num_classes)], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """Parses the idx-ubyte format (reference: vision/datasets/mnist.py).

    Pass image_path/label_path to local (optionally .gz) idx files; with
    no paths, falls back to FakeMNIST-style synthetic data so smoke
    pipelines run hermetically.
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None, **fake_kwargs):
        self.mode = mode
        self.transform = transform
        if image_path is None and label_path is None:
            fake = FakeMNIST(mode=mode, **fake_kwargs)
            self.images = (fake.images[:, 0] * 255).astype(np.uint8)
            self.labels = fake.labels
            return
        image_path = _require(image_path, f"{self.NAME} images")
        label_path = _require(label_path, f"{self.NAME} labels")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype(
                np.int64).reshape(-1, 1)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    """Parses the python-pickle cifar tar archive (reference: cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None:
            rng = np.random.RandomState(0)
            n = 512
            self.data = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)
            self.labels = rng.randint(0, self._num_classes, (n,)).astype(
                np.int64)
            return
        data_file = _require(data_file, self._archive)
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if not member.isfile() or not self._member_matches(base, mode):
                    continue
                batch = pickle.load(tf.extractfile(member),
                                    encoding="latin1")
                datas.append(np.asarray(batch["data"], dtype=np.uint8))
                labels.extend(batch[self._label_key])
        if not datas:
            raise ValueError(f"no {mode} batches found in {data_file}")
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar10(_CifarBase):
    _num_classes = 10
    _archive = "cifar-10-python.tar.gz"
    _label_key = "labels"

    @staticmethod
    def _member_matches(name, mode):
        return name.startswith("data_batch") if mode == "train" \
            else name == "test_batch"


class Cifar100(_CifarBase):
    _num_classes = 100
    _archive = "cifar-100-python.tar.gz"
    _label_key = "fine_labels"

    @staticmethod
    def _member_matches(name, mode):
        return name == ("train" if mode == "train" else "test")


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                   ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def has_valid_extension(filename, extensions=_IMG_EXTENSIONS):
    return filename.lower().endswith(tuple(extensions))


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    """(path, class_index) samples from a class-per-subdir tree
    (reference: vision/datasets/folder.py make_dataset)."""
    samples = []
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions or _IMG_EXTENSIONS)
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """Generic class-per-subdirectory image dataset
    (reference: vision/datasets/folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise ValueError(f"no class subdirectories found under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx, extensions,
                                    is_valid_file)
        if not self.samples:
            raise ValueError(f"no valid image files found under {root}")
        self.targets = [s[1] for s in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (unlabelled) image folder (reference: folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        exts = extensions or _IMG_EXTENSIONS

        def valid(p):
            return is_valid_file(p) if is_valid_file else \
                has_valid_extension(p, exts)

        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                p = os.path.join(r, fname)
                if valid(p):
                    samples.append(p)
        if not samples:
            raise ValueError(f"no valid image files found under {root}")
        self.samples = samples

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class _LazyTarMixin:
    """Per-process tar handle: forked DataLoader workers must not share
    one fd/offset (the reference avoids this by extracting to disk)."""

    def _tar_init(self, path):
        self._tar_path = path
        self._tar_handles = {}
        with tarfile.open(path, "r:*") as tf:
            members = tf.getmembers()
        return members

    @property
    def _tar(self):
        pid = os.getpid()
        tf = self._tar_handles.get(pid)
        if tf is None:
            tf = tarfile.open(self._tar_path, "r:*")
            self._tar_handles = {pid: tf}  # drop inherited handles
        return tf


class Flowers(_LazyTarMixin, Dataset):
    """Oxford 102 flowers (reference: vision/datasets/flowers.py).

    Requires local archives: data_file (102flowers.tgz), label_file
    (imagelabels.mat), setid_file (setid.mat); .mat parsing via scipy as
    in the reference.
    """

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        data_file = _require(data_file, "flowers images (102flowers.tgz)")
        label_file = _require(label_file, "flowers labels (imagelabels.mat)")
        setid_file = _require(setid_file, "flowers split ids (setid.mat)")
        import scipy.io as sio
        self.labels = sio.loadmat(label_file)["labels"][0]
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key][0]
        members = self._tar_init(data_file)
        self._names = {os.path.basename(m.name): m
                       for m in members if m.isfile()}

    def __getitem__(self, idx):
        from PIL import Image
        flower_id = int(self.indexes[idx])
        member = self._names[f"image_{flower_id:05d}.jpg"]
        img = Image.open(self._tar.extractfile(member)).convert("RGB")
        label = np.asarray([self.labels[flower_id - 1]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(_LazyTarMixin, Dataset):
    """Pascal VOC2012 segmentation pairs (reference: voc2012.py).

    data_file: local VOCtrainval tar archive.
    """

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = _require(data_file, "VOCtrainval_11-May-2012.tar")
        names = {m.name: m for m in self._tar_init(data_file)}
        split = "trainval" if mode == "trainval" else mode
        seg_list = None
        for n in names:
            if n.endswith(f"ImageSets/Segmentation/{split}.txt"):
                seg_list = n
                break
        if seg_list is None:
            raise ValueError(f"no segmentation split '{mode}' in archive")
        ids = self._tar.extractfile(names[seg_list]).read().decode().split()
        root = seg_list.split("ImageSets/")[0]
        self._pairs = [
            (names[f"{root}JPEGImages/{i}.jpg"],
             names[f"{root}SegmentationClass/{i}.png"]) for i in ids]

    def __getitem__(self, idx):
        from PIL import Image
        im, lm = self._pairs[idx]
        img = Image.open(self._tar.extractfile(im)).convert("RGB")
        label = Image.open(self._tar.extractfile(lm))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self._pairs)
