"""Synthetic stand-ins for vision datasets (no network egress in this env).
The reference downloads MNIST etc. (python/paddle/vision/datasets/); here
FakeMNIST/FakeImageNet generate deterministic data with the same shapes so
training pipelines and benchmarks run hermetically.
"""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset


class FakeMNIST(Dataset):
    def __init__(self, mode="train", n=1024, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(n, 1, 28, 28).astype(np.float32)
        self.labels = rng.randint(0, 10, (n, 1)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


MNIST = FakeMNIST


class FakeImageNet(Dataset):
    def __init__(self, n=256, image_size=224, num_classes=1000, seed=0,
                 transform=None):
        rng = np.random.RandomState(seed)
        self.n = n
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed
        self.transform = transform

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(3, self.image_size, self.image_size).astype(np.float32)
        label = np.asarray([rng.randint(0, self.num_classes)], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.n
