from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
