from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401

_image_backend = "pil"


def set_image_backend(backend):
    """(reference vision/image.py): 'pil' or 'cv2' — this build ships
    PIL; cv2 is not in the image."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got "
                         f"{backend!r}")
    if backend == "cv2":
        raise RuntimeError("cv2 is not available in this environment; "
                           "the PIL backend is")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file via the selected backend (reference
    vision/image.py image_load returns a PIL Image for 'pil')."""
    backend = backend or _image_backend
    if backend != "pil":
        raise RuntimeError(f"backend {backend!r} unavailable (PIL only)")
    from PIL import Image

    return Image.open(path)
