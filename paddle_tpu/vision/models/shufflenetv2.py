"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py).

Channel-split inverted residuals with channel shuffle.
"""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, split


def _act(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


class ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, groups=1, act="relu",
                 use_act=True):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride,
                              padding=(kernel - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = _act(act) if use_act else nn.Identity()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class InvertedResidual(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        c = channels // 2
        self.branch = nn.Sequential(
            ConvBNAct(c, c, 1, act=act),
            ConvBNAct(c, c, 3, groups=c, use_act=False, act=act),
            ConvBNAct(c, c, 1, act=act),
        )
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        out = concat([x1, self.branch(x2)], axis=1)
        return self.shuffle(out)


class InvertedResidualDS(nn.Layer):
    """stride-2 downsampling unit: both halves transformed."""

    def __init__(self, cin, cout, act):
        super().__init__()
        c = cout // 2
        self.branch1 = nn.Sequential(
            ConvBNAct(cin, cin, 3, stride=2, groups=cin, use_act=False,
                      act=act),
            ConvBNAct(cin, c, 1, act=act),
        )
        self.branch2 = nn.Sequential(
            ConvBNAct(cin, c, 1, act=act),
            ConvBNAct(c, c, 3, stride=2, groups=c, use_act=False, act=act),
            ConvBNAct(c, c, 1, act=act),
        )
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        chans = _STAGE_OUT[scale]
        self.conv1 = ConvBNAct(3, chans[0], 3, stride=2, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        cin = chans[0]
        for stage, reps in enumerate(_STAGE_REPEATS):
            cout = chans[stage + 1]
            blocks.append(InvertedResidualDS(cin, cout, act))
            for _ in range(reps - 1):
                blocks.append(InvertedResidual(cout, act))
            cin = cout
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = ConvBNAct(cin, chans[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.blocks(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
