"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py).

Depthwise-separable conv stacks; on TPU the depthwise convs lower to
XLA's feature-group convolutions.
"""
from __future__ import annotations

from ... import nn


class ConvBNLayer(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, num_groups=1):
        super().__init__()
        self._conv = nn.Conv2D(in_channels, out_channels, kernel_size,
                               stride=stride, padding=padding,
                               groups=num_groups, bias_attr=False)
        self._norm = nn.BatchNorm2D(out_channels)
        self._act = nn.ReLU()

    def forward(self, x):
        return self._act(self._norm(self._conv(x)))


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self._depthwise = ConvBNLayer(
            in_channels, int(out_channels1 * scale), 3, stride=stride,
            padding=1, num_groups=int(num_groups * scale))
        self._pointwise = ConvBNLayer(
            int(out_channels1 * scale), int(out_channels2 * scale), 1)

    def forward(self, x):
        return self._pointwise(self._depthwise(x))


class MobileNetV1(nn.Layer):
    """scale: width multiplier applied to every channel count."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        # (in, dw_out, pw_out, groups, stride)
        cfg = [
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1),
        ]
        blocks = []
        for cin, dw, pw, g, s in cfg:
            blocks.append(DepthwiseSeparable(
                int(cin * scale), dw, pw, g, s, scale))
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
