"""InceptionV3 (reference: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat


class ConvBNReLU(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InceptionA(nn.Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = ConvBNReLU(cin, 64, 1)
        self.b5 = nn.Sequential(ConvBNReLU(cin, 48, 1),
                                ConvBNReLU(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(ConvBNReLU(cin, 64, 1),
                                ConvBNReLU(64, 96, 3, padding=1),
                                ConvBNReLU(96, 96, 3, padding=1))
        self.pool = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                  ConvBNReLU(cin, pool_features, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.pool(x)],
                      axis=1)


class InceptionB(nn.Layer):
    """grid reduction 35->17"""

    def __init__(self, cin):
        super().__init__()
        self.b3 = ConvBNReLU(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(ConvBNReLU(cin, 64, 1),
                                 ConvBNReLU(64, 96, 3, padding=1),
                                 ConvBNReLU(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class InceptionC(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = ConvBNReLU(cin, 192, 1)
        self.b7 = nn.Sequential(
            ConvBNReLU(cin, c7, 1),
            ConvBNReLU(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNReLU(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            ConvBNReLU(cin, c7, 1),
            ConvBNReLU(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNReLU(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNReLU(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNReLU(c7, 192, (1, 7), padding=(0, 3)))
        self.pool = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                  ConvBNReLU(cin, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.pool(x)],
                      axis=1)


class InceptionD(nn.Layer):
    """grid reduction 17->8"""

    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(ConvBNReLU(cin, 192, 1),
                                ConvBNReLU(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            ConvBNReLU(cin, 192, 1),
            ConvBNReLU(192, 192, (1, 7), padding=(0, 3)),
            ConvBNReLU(192, 192, (7, 1), padding=(3, 0)),
            ConvBNReLU(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class InceptionE(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = ConvBNReLU(cin, 320, 1)
        self.b3_stem = ConvBNReLU(cin, 384, 1)
        self.b3_a = ConvBNReLU(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = ConvBNReLU(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(ConvBNReLU(cin, 448, 1),
                                      ConvBNReLU(448, 384, 3, padding=1))
        self.b3d_a = ConvBNReLU(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = ConvBNReLU(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                  ConvBNReLU(cin, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        b3 = concat([self.b3_a(s), self.b3_b(s)], axis=1)
        d = self.b3d_stem(x)
        b3d = concat([self.b3d_a(d), self.b3d_b(d)], axis=1)
        return concat([self.b1(x), b3, b3d, self.pool(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            ConvBNReLU(3, 32, 3, stride=2),
            ConvBNReLU(32, 32, 3),
            ConvBNReLU(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            ConvBNReLU(64, 80, 1),
            ConvBNReLU(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.drop(x)
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
