"""MobileNetV3 small/large (reference: python/paddle/vision/models/mobilenetv3.py).

Inverted residuals with squeeze-excitation and hardswish.
"""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcitation(nn.Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(input_channels, squeeze_channels, 1)
        self.fc2 = nn.Conv2D(squeeze_channels, input_channels, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.avgpool(x)
        s = self.relu(self.fc1(s))
        s = self.hsig(self.fc2(s))
        return x * s


class ConvNormActivation(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, groups=1, act="relu"):
        super().__init__()
        pad = (kernel - 1) // 2
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride, padding=pad,
                              groups=groups, bias_attr=False)
        self.norm = nn.BatchNorm2D(cout)
        self.act = {"relu": nn.ReLU, "hardswish": nn.Hardswish,
                    None: nn.Identity}[act]()

    def forward(self, x):
        return self.act(self.norm(self.conv(x)))


class InvertedResidual(nn.Layer):
    def __init__(self, cin, exp, cout, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if exp != cin:
            layers.append(ConvNormActivation(cin, exp, 1, act=act))
        layers.append(ConvNormActivation(exp, exp, kernel, stride=stride,
                                         groups=exp, act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp, _make_divisible(exp // 4)))
        layers.append(ConvNormActivation(exp, cout, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, use_se, act, stride)
_LARGE_CFG = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL_CFG = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        sc = lambda c: _make_divisible(c * scale)

        first = sc(16)
        layers = [ConvNormActivation(3, first, 3, stride=2, act="hardswish")]
        cin = first
        for kernel, exp, cout, use_se, act, stride in config:
            layers.append(InvertedResidual(cin, sc(exp), sc(cout), kernel,
                                           stride, use_se, act))
            cin = sc(cout)
        lastconv = _make_divisible(sc(config[-1][2]) * 6)
        layers.append(ConvNormActivation(cin, lastconv, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE_CFG, _make_divisible(1280 * scale),
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL_CFG, _make_divisible(1024 * scale),
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
