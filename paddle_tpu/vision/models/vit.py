"""Vision Transformer (reference vision zoo ViT; TPU-native: patch
embedding is one conv, encoder blocks share the flash-attention path)."""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn import functional as F
from ...nn.initializer_utils import create_parameter_with_attr
from ...nn import initializer as I


class PatchEmbed(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_chans, embed_dim, patch_size,
                              stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                       # [B, E, H/ps, W/ps]
        b, e = x.shape[0], x.shape[1]
        return x.reshape([b, e, -1]).transpose([0, 2, 1])  # [B, N, E]


class ViTBlock(nn.Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, dropout=0.0):
        super().__init__()
        self.ln1 = nn.LayerNorm(dim)
        self.attn = nn.MultiHeadAttention(dim, num_heads, dropout=dropout)
        self.ln2 = nn.LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(dim, hidden), nn.GELU(),
                                 nn.Dropout(dropout),
                                 nn.Linear(hidden, dim),
                                 nn.Dropout(dropout))

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self.mlp(self.ln2(x))


class VisionTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 num_classes=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, dropout=0.0):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim)
        n = self.patch_embed.num_patches
        init = I.Normal(std=0.02)
        self.cls_token = create_parameter_with_attr(
            [1, 1, embed_dim], self._dtype, None, False,
            default_initializer=init)
        self.pos_embed = create_parameter_with_attr(
            [1, n + 1, embed_dim], self._dtype, None, False,
            default_initializer=init)
        self.blocks = nn.LayerList([
            ViTBlock(embed_dim, num_heads, mlp_ratio, dropout)
            for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim)
        self.head = nn.Linear(embed_dim, num_classes)

    def forward(self, x):
        from ...tensor.manipulation import concat, expand
        x = self.patch_embed(x)
        b = x.shape[0]
        cls = expand(self.cls_token, [b, 1, x.shape[-1]])
        x = concat([cls, x], axis=1) + self.pos_embed
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.norm(x)[:, 0])


def vit_b_16(pretrained=False, **kwargs):
    kwargs.setdefault("embed_dim", 768)
    kwargs.setdefault("depth", 12)
    kwargs.setdefault("num_heads", 12)
    return VisionTransformer(patch_size=16, **kwargs)


def vit_s_16(pretrained=False, **kwargs):
    kwargs.setdefault("embed_dim", 384)
    kwargs.setdefault("depth", 12)
    kwargs.setdefault("num_heads", 6)
    return VisionTransformer(patch_size=16, **kwargs)
