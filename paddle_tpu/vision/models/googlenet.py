"""GoogLeNet / InceptionV1 (reference: python/paddle/vision/models/googlenet.py).

forward returns [out, aux1, aux2] like the reference.
"""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat


class ConvReLU(nn.Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, kernel, stride=stride,
                              padding=padding)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = ConvReLU(cin, c1, 1)
        self.b2 = nn.Sequential(ConvReLU(cin, c3r, 1),
                                ConvReLU(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(ConvReLU(cin, c5r, 1),
                                ConvReLU(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                ConvReLU(cin, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class AuxHead(nn.Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = ConvReLU(cin, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = x.reshape([x.shape[0], -1])
        x = self.drop(self.relu(self.fc1(x)))
        return self.fc2(x)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            ConvReLU(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            ConvReLU(64, 64, 1),
            ConvReLU(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
        )
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = AuxHead(512, num_classes)
            self.aux2 = AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            out = self.fc(self.drop(x))
            return [out, aux1, aux2]
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
