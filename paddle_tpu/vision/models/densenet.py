"""DenseNet 121/161/169/201/264 (reference: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat


class DenseLayer(nn.Layer):
    """BN-ReLU-Conv1x1 -> BN-ReLU-Conv3x3, output concatenated to input."""

    def __init__(self, cin, growth_rate, bn_size, dropout):
        super().__init__()
        self.dropout = dropout
        inter = bn_size * growth_rate
        self.norm1 = nn.BatchNorm2D(cin)
        self.conv1 = nn.Conv2D(cin, inter, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(inter)
        self.conv2 = nn.Conv2D(inter, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv1(self.relu(self.norm1(x)))
        y = self.conv2(self.relu(self.norm2(y)))
        if self.drop is not None:
            y = self.drop(y)
        return concat([x, y], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, cin, num_layers, growth_rate, bn_size, dropout):
        super().__init__()
        layers = []
        for i in range(num_layers):
            layers.append(DenseLayer(cin + i * growth_rate, growth_rate,
                                     bn_size, dropout))
        self.layers = nn.Sequential(*layers)

    def forward(self, x):
        return self.layers(x)


class TransitionLayer(nn.Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm = nn.BatchNorm2D(cin)
        self.conv = nn.Conv2D(cin, cout, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"unsupported DenseNet depth {layers}")
        num_init, growth, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.norm1 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        c = num_init
        for i, n in enumerate(block_cfg):
            blocks.append(DenseBlock(c, n, growth, bn_size, dropout))
            c = c + n * growth
            if i != len(block_cfg) - 1:
                blocks.append(TransitionLayer(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(c)
        if with_pool:
            self.pool_final = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.pool1(self.relu(self.norm1(self.conv1(x))))
        x = self.blocks(x)
        x = self.relu(self.norm_final(x))
        if self.with_pool:
            x = self.pool_final(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(layers=121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(layers=161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(layers=169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(layers=201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(layers=264, **kwargs)
