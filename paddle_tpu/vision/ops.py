"""paddle.vision.ops — detection / region ops.

Reference: python/paddle/vision/ops.py (yolo_box, prior_box, box_coder,
deform_conv2d, roi_pool/roi_align/psroi_pool, nms) backed by PHI CUDA
kernels. TPU-native design: the dense, differentiable ops (roi_align,
deform_conv2d, box decode) are vectorized gather/interp compositions that
XLA fuses; greedy NMS is data-dependent and sequential, so the
suppression scan runs as a bounded `lax.fori_loop` over a precomputed IoU
matrix, then syncs the kept mask to the host to build the
variable-length index result (these post-processing ops are eager-only,
as in the reference's detection heads).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import apply_op, wrap
from ..core.tensor import Tensor
from .. import nn

__all__ = [
    "yolo_box", "prior_box", "box_coder", "deform_conv2d", "DeformConv2D",
    "roi_pool", "RoIPool", "roi_align", "RoIAlign", "psroi_pool", "PSRoIPool",
    "nms", "matrix_nms", "distribute_fpn_proposals",
]


# ---------------------------------------------------------------- box utils

def _iou_matrix(boxes, offset=0.0):
    """boxes (N,4) xyxy -> (N,N) IoU. offset=1 for pixel (unnormalized)
    coordinates, as in the reference kernels' `normalized=False` mode."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1 + offset, 0) * jnp.maximum(y2 - y1 + offset, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = (jnp.maximum(ix2 - ix1 + offset, 0)
             * jnp.maximum(iy2 - iy1 + offset, 0))
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy hard NMS; returns kept indices sorted by descending score.

    Matches reference python/paddle/vision/ops.py:nms — supports
    category-aware batched NMS via the coordinate-offset trick.
    """
    b = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = b.shape[0]
    if n == 0:
        return wrap(jnp.zeros((0,), dtype=jnp.int64))
    if scores is None:
        s = jnp.arange(n, 0, -1, dtype=jnp.float32)
    else:
        s = scores._data if isinstance(scores, Tensor) else jnp.asarray(scores)
    if category_idxs is not None:
        cidx = (category_idxs._data if isinstance(category_idxs, Tensor)
                else jnp.asarray(category_idxs))
        # offset every category into a disjoint coordinate range so one
        # global NMS never suppresses across categories
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cidx.astype(b.dtype) * span)[:, None]

    order = jnp.argsort(-s)
    bs = b[order]
    iou = _iou_matrix(bs)

    def body(i, keep):
        # drop i if it overlaps any higher-scoring kept box
        sup = jnp.any((iou[i] > iou_threshold) & keep & (jnp.arange(n) < i))
        return keep.at[i].set(~sup & keep[i])

    keep = lax.fori_loop(0, n, body, jnp.ones((n,), dtype=bool))
    kept_sorted = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return wrap(jnp.asarray(kept_sorted, dtype=jnp.int64))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Parallel (matrix) soft-NMS — decay each score by worst overlap
    with any higher-scoring box of the same class.

    Reference: python/paddle/vision/ops.py matrix_nms (PHI matrix_nms op).
    Single-image, fully vectorized.
    """
    bb = bboxes._data if isinstance(bboxes, Tensor) else jnp.asarray(bboxes)
    sc = scores._data if isinstance(scores, Tensor) else jnp.asarray(scores)
    # bb: (1, M, 4); sc: (1, C, M)
    bb2, sc2 = bb[0], sc[0]
    C, M = sc2.shape
    rows = []  # (decayed_score, class, box, orig_idx)
    for c in range(C):
        if c == background_label:
            continue
        s = np.asarray(sc2[c])
        sel = np.nonzero(s > score_threshold)[0]
        if sel.size == 0:
            continue
        sel = sel[np.argsort(-s[sel])][:nms_top_k]
        boxes_c = bb2[sel]
        sc_c = jnp.asarray(s[sel])
        n = sel.shape[0]
        iou = _iou_matrix(boxes_c, offset=0.0 if normalized else 1.0)
        ntri = jnp.tril(iou, -1)  # row i: overlaps with higher-scored j<i
        comp = jnp.max(ntri, axis=1)  # worst overlap of each box w/ its preds
        if use_gaussian:
            dec = jnp.exp(-(ntri ** 2 - comp[None, :] ** 2) * gaussian_sigma)
        else:
            dec = (1 - ntri) / jnp.maximum(1 - comp[None, :], 1e-9)
        lower = jnp.tril(jnp.ones((n, n), dtype=bool), -1)
        decay = jnp.min(jnp.where(lower, dec, 1.0), axis=1)
        decay = jnp.minimum(decay, 1.0)  # never increase a score
        dec_scores = sc_c * decay
        keep = np.asarray(dec_scores) > post_threshold
        for k, orig in zip(np.asarray(dec_scores)[keep], sel[keep]):
            rows.append((float(k), float(c), np.asarray(bb2[orig]),
                         int(orig)))
    rows.sort(key=lambda r: -r[0])
    rows = rows[:keep_top_k]
    outs = [[r[1], r[0]] + list(r[2]) for r in rows]
    idxs = [r[3] for r in rows]
    out = wrap(jnp.asarray(outs, dtype=jnp.float32).reshape(-1, 6))
    rois_num = wrap(jnp.asarray([len(outs)], dtype=jnp.int32))
    res = [out]
    if return_rois_num:
        res.append(rois_num)
    if return_index:
        res.append(wrap(jnp.asarray(idxs, dtype=jnp.int32)))
    return tuple(res) if len(res) > 1 else out


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes + scores.

    Reference: python/paddle/vision/ops.py yolo_box (PHI yolo_box kernel).
    x: (N, S*(5+class_num), H, W) -> boxes (N, S*H*W, 4), scores
    (N, S*H*W, class_num); rows are anchor-major (row k is anchor
    k//(H*W), cell ((k%(H*W))//W, k%W)), matching the reference layout.
    """
    s = len(anchors) // 2
    anc = jnp.asarray(anchors, dtype=jnp.float32).reshape(s, 2)

    def fn(a, imgs):
        n, _, h, w = a.shape
        a = a.reshape(n, s, 5 + class_num + (1 if iou_aware else 0), h, w)
        if iou_aware:
            ioup, a = a[:, :, :1], a[:, :, 1:]
        gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
        gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(a[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / w
        by = (sig(a[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / h
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        bw = jnp.exp(a[:, :, 2]) * anc[None, :, 0, None, None] / input_w
        bh = jnp.exp(a[:, :, 3]) * anc[None, :, 1, None, None] / input_h
        conf = sig(a[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * \
                sig(ioup[:, :, 0]) ** iou_aware_factor
        prob = sig(a[:, :, 5:]) * conf[:, :, None]
        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imgw - 1)
            y2 = jnp.minimum(y2, imgh - 1)
        # anchor-major flattening (reference kernel box_idx =
        # anchor*h*w + row*w + col)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # (n,s,h,w,4)
        boxes = boxes.reshape(n, s * h * w, 4)
        scores = prob.transpose(0, 1, 3, 4, 2).reshape(
            n, s * h * w, class_num)
        mask = conf.reshape(n, s * h * w) > conf_thresh
        boxes = boxes * mask[..., None].astype(boxes.dtype)
        scores = scores * mask[..., None].astype(scores.dtype)
        return boxes, scores

    return apply_op("yolo_box", fn, x, img_size)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) box generation.

    Reference: python/paddle/vision/ops.py prior_box (PHI prior_box).
    """
    inp = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    img = image._data if isinstance(image, Tensor) else jnp.asarray(image)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    step_w = steps[0] or img_w / w
    step_h = steps[1] or img_h / h

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []  # (box_w, box_h) in pixels, ordering per reference kernel
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                sq = float(np.sqrt(ms * float(max_sizes[k])))
                whs.append((sq, sq))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                sq = float(np.sqrt(ms * float(max_sizes[k])))
                whs.append((sq, sq))
    whs = np.asarray(whs, dtype=np.float32)  # (P, 2)
    P = whs.shape[0]

    cx = (np.arange(w, dtype=np.float32) + offset) * step_w
    cy = (np.arange(h, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # (h, w)
    out = np.zeros((h, w, P, 4), dtype=np.float32)
    out[..., 0] = (cxg[:, :, None] - whs[None, None, :, 0] / 2) / img_w
    out[..., 1] = (cyg[:, :, None] - whs[None, None, :, 1] / 2) / img_h
    out[..., 2] = (cxg[:, :, None] + whs[None, None, :, 0] / 2) / img_w
    out[..., 3] = (cyg[:, :, None] + whs[None, None, :, 1] / 2) / img_h
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, dtype=np.float32),
                          out.shape).copy()
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (R-CNN style deltas).

    Reference: python/paddle/vision/ops.py box_coder (PHI box_coder).
    """
    pb = prior_box._data if isinstance(prior_box, Tensor) else jnp.asarray(prior_box)
    pbv = None
    if prior_box_var is not None and not isinstance(prior_box_var, (list, tuple)):
        pbv = (prior_box_var._data if isinstance(prior_box_var, Tensor)
               else jnp.asarray(prior_box_var))
    elif isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, dtype=jnp.float32)

    norm = 0.0 if box_normalized else 1.0

    def fn(tb):
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)  # (T, P, 4)
            if pbv is not None:
                out = out / (pbv if pbv.ndim == 1 else pbv[None, :, :])
            return out
        # decode_center_size: tb (T, P, 4) deltas against priors
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
        d = tb
        if pbv is not None:
            v = pbv
            if v.ndim == 1:
                d = d * v
            else:
                d = d * (v[None, :, :] if axis == 0 else v[:, None, :])
        ocx = d[..., 0] * pw_ + pcx_
        ocy = d[..., 1] * ph_ + pcy_
        ow = jnp.exp(d[..., 2]) * pw_
        oh = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                          ocx + ow / 2 - norm, ocy + oh / 2 - norm], axis=-1)

    return apply_op("box_coder", fn, target_box)


# ------------------------------------------------------------- roi pooling

def _rois_to_batch(boxes, boxes_num):
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor)
                    else boxes_num)
    batch_idx = np.repeat(np.arange(bn.shape[0]), bn)
    return jnp.asarray(batch_idx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign with bilinear sampling (Mask R-CNN).

    Reference: python/paddle/vision/ops.py roi_align (PHI roi_align).
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _rois_to_batch(boxes, boxes_num)
    # Per-roi adaptive sampling density (reference roi_align kernel:
    # sampling_ratio<=0 -> ceil(roi_size/output_size) per roi). Boxes are
    # host data on this eager path, so group rois that share a density and
    # vmap within each group.
    bnp = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes,
                     dtype=np.float64)
    rh_np = bnp[:, 3] * spatial_scale - bnp[:, 1] * spatial_scale
    rw_np = bnp[:, 2] * spatial_scale - bnp[:, 0] * spatial_scale
    if not aligned:
        rh_np = np.maximum(rh_np, 1.0)
        rw_np = np.maximum(rw_np, 1.0)
    if sampling_ratio > 0:
        sr_h = np.full(bnp.shape[0], sampling_ratio, dtype=np.int64)
        sr_w = sr_h
    else:
        sr_h = np.maximum(np.ceil(rh_np / ph), 1).astype(np.int64)
        sr_w = np.maximum(np.ceil(rw_np / pw), 1).astype(np.int64)

    def fn(feat, bx):
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        H, W = feat.shape[2], feat.shape[3]

        def bilinear(img, ys, xs):
            # img (C,H,W); ys (ny,), xs (nx,) -> (C, ny, nx). Samples
            # farther than 1px outside the map contribute 0 (reference
            # kernel's y < -1 || y > height rule).
            vy = (ys >= -1.0) & (ys <= H)
            vx = (xs >= -1.0) & (xs <= W)
            ys = jnp.clip(ys, 0.0, H - 1.0)
            xs = jnp.clip(xs, 0.0, W - 1.0)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = ys - y0
            wx = xs - x0
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1_]
            v10 = img[:, y1_][:, :, x0]
            v11 = img[:, y1_][:, :, x1_]
            out = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                   + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                   + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                   + v11 * wy[None, :, None] * wx[None, None, :])
            return out * (vy[:, None] & vx[None, :])[None].astype(out.dtype)

        def roi_group(ridx, sh, sw):
            # sample grids for rois in ridx, all sharing density (sh, sw)
            iy = (jnp.arange(sh, dtype=feat.dtype) + 0.5) / sh
            ix = (jnp.arange(sw, dtype=feat.dtype) + 0.5) / sw
            yy = (y1[ridx][:, None, None]
                  + (jnp.arange(ph, dtype=feat.dtype)[None, :, None]
                     + iy[None, None, :]) * bin_h[ridx][:, None, None])
            xx = (x1[ridx][:, None, None]
                  + (jnp.arange(pw, dtype=feat.dtype)[None, :, None]
                     + ix[None, None, :]) * bin_w[ridx][:, None, None])

            def per_roi(r):
                img = feat[batch_idx[ridx][r]]
                s = bilinear(img, yy[r].reshape(-1), xx[r].reshape(-1))
                C = s.shape[0]
                return s.reshape(C, ph, sh, pw, sw).mean(axis=(2, 4))

            return jax.vmap(per_roi)(jnp.arange(len(ridx)))

        R = bnp.shape[0]
        if R == 0:
            return jnp.zeros((0, feat.shape[1], ph, pw), feat.dtype)
        groups = {}
        for r in range(R):
            groups.setdefault((int(sr_h[r]), int(sr_w[r])), []).append(r)
        pieces = [None] * R
        for (sh, sw), ridx in groups.items():
            out_g = roi_group(jnp.asarray(ridx), sh, sw)
            for k, r in enumerate(ridx):
                pieces[r] = out_g[k]
        return jnp.stack(pieces)

    return apply_op("roi_align", fn, x, boxes)


def _bin_masks(starts, lens, P, D, quantize):
    """Per-roi bin membership masks (R, P, D) computed host-side.

    starts/lens: (R,) float roi start + extent; bin i of roi r covers
    [start + floor/… , …) rows per the reference's quantization rule.
    """
    R = starts.shape[0]
    m = np.zeros((R, P, D), dtype=bool)
    for r in range(R):
        for i in range(P):
            if quantize == "inner":  # roi_pool: integer start + floor(len)
                lo = starts[r] + np.floor(i * lens[r] / P)
                hi = starts[r] + np.ceil((i + 1) * lens[r] / P)
            else:  # psroi_pool: floor/ceil applied to the float boundary
                lo = np.floor(starts[r] + i * lens[r] / P)
                hi = np.ceil(starts[r] + (i + 1) * lens[r] / P)
            lo, hi = int(max(lo, 0)), int(min(hi, D))
            if hi > lo:
                m[r, i, lo:hi] = True
    return m


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool: max over quantized bins (Fast R-CNN).

    Reference: python/paddle/vision/ops.py roi_pool (PHI roi_pool).
    Vectorized as two masked max-reductions (over W then H) so all rois
    resolve in a handful of XLA ops instead of per-bin slicing.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = np.asarray(_rois_to_batch(boxes, boxes_num))
    bx = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    R = bx.shape[0]

    def fn(feat):
        H, W = feat.shape[2], feat.shape[3]
        if R == 0:
            return jnp.zeros((0, feat.shape[1], ph, pw), feat.dtype)
        # half-away-from-zero rounding (C round), not numpy's half-to-even
        x1 = np.floor(bx[:, 0] * spatial_scale + 0.5)
        y1 = np.floor(bx[:, 1] * spatial_scale + 0.5)
        x2 = np.floor(bx[:, 2] * spatial_scale + 0.5)
        y2 = np.floor(bx[:, 3] * spatial_scale + 0.5)
        rh = np.maximum(y2 - y1 + 1, 1)
        rw = np.maximum(x2 - x1 + 1, 1)
        mh = jnp.asarray(_bin_masks(y1, rh, ph, H, "inner"))  # (R, ph, H)
        mw = jnp.asarray(_bin_masks(x1, rw, pw, W, "inner"))  # (R, pw, W)
        fr = feat[jnp.asarray(batch_idx)]  # (R, C, H, W)
        neg = jnp.asarray(-jnp.inf, feat.dtype)
        # max over W within each w-bin -> (R, C, H, pw)
        t1 = jnp.max(jnp.where(mw[:, None, None, :, :],
                               fr[:, :, :, None, :], neg), axis=-1)
        # max over H within each h-bin -> (R, C, ph, pw)
        t2 = jnp.max(jnp.where(mh[:, None, :, None, :],
                               jnp.moveaxis(t1, 2, 3)[:, :, None], neg),
                     axis=-1)
        return jnp.where(jnp.isfinite(t2), t2, 0.0)

    return apply_op("roi_pool", fn, x)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN).

    Reference: python/paddle/vision/ops.py psroi_pool (PHI psroi_pool).
    Channels C must equal out_c * ph * pw.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = np.asarray(_rois_to_batch(boxes, boxes_num))
    bx = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    R = bx.shape[0]

    def fn(feat):
        N, C, H, W = feat.shape
        out_c = C // (ph * pw)
        if R == 0:
            return jnp.zeros((0, out_c, ph, pw), feat.dtype)
        # reference kernel: roi_start = round(c)*scale, roi_end =
        # round(c+1)*scale, extent floored at 0.1
        x1 = np.floor(bx[:, 0] + 0.5) * spatial_scale
        y1 = np.floor(bx[:, 1] + 0.5) * spatial_scale
        x2 = np.floor(bx[:, 2] + 1 + 0.5) * spatial_scale
        y2 = np.floor(bx[:, 3] + 1 + 0.5) * spatial_scale
        rh = np.maximum(y2 - y1, 0.1)
        rw = np.maximum(x2 - x1, 0.1)
        mh = jnp.asarray(_bin_masks(y1, rh, ph, H, "outer"),
                         dtype=feat.dtype)  # (R, ph, H)
        mw = jnp.asarray(_bin_masks(x1, rw, pw, W, "outer"),
                         dtype=feat.dtype)  # (R, pw, W)
        # position-sensitive channel layout: channel (c*ph + i)*pw + j
        fr = feat[jnp.asarray(batch_idx)].reshape(R, out_c, ph, pw, H, W)
        s = jnp.einsum("rcijhw,rih,rjw->rcij", fr, mh, mw)
        cnt = jnp.einsum("rih,rjw->rij", mh, mw)[:, None]
        return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1.0), 0.0)

    return apply_op("psroi_pool", fn, x)


# ------------------------------------------------------- deformable conv

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 as bilinear gather + dense matmul.

    Reference: python/paddle/vision/ops.py deform_conv2d (PHI
    deformable_conv kernel). The im2col+offset sampling is expressed as a
    vectorized bilinear interpolation so XLA maps the contraction on the
    MXU; mask!=None selects v2 (modulated).
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def fn(*arrs):
        if mask is not None:
            a, off, w_, m = arrs[0], arrs[1], arrs[2], arrs[3]
            rest = arrs[4:]
        else:
            a, off, w_ = arrs[0], arrs[1], arrs[2]
            m = None
            rest = arrs[3:]
        b_ = rest[0] if rest else None
        N, C, H, W = a.shape
        Cout, Cin_g, kh, kw = w_.shape
        pad_a = jnp.pad(a, ((0, 0), (0, 0), (padding[0], padding[0]),
                            (padding[1], padding[1])))
        Hp, Wp = pad_a.shape[2], pad_a.shape[3]
        Ho = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) // stride[0] + 1
        Wo = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) // stride[1] + 1
        # base sampling grid (kh*kw, Ho, Wo)
        oy = jnp.arange(Ho) * stride[0]
        ox = jnp.arange(Wo) * stride[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = (oy[None, :, None] + ky[:, None, None]).astype(jnp.float32)
        base_x = (ox[None, None, :] + kx[:, None, None]).astype(jnp.float32)
        base_y = jnp.broadcast_to(base_y[:, None], (kh, kw, Ho, Wo)).reshape(
            kh * kw, Ho, Wo)
        base_x = jnp.broadcast_to(base_x[None, :], (kh, kw, Ho, Wo)).reshape(
            kh * kw, Ho, Wo)
        # offsets: (N, dg*2*kh*kw, Ho, Wo) ordered (y, x) per kernel point
        off = off.reshape(N, deformable_groups, kh * kw, 2, Ho, Wo)
        sy = base_y[None, None] + off[:, :, :, 0]
        sx = base_x[None, None] + off[:, :, :, 1]

        cg = C // deformable_groups

        def bilinear_nc(img, ys, xs):
            # img (cg, Hp, Wp), ys/xs (kk, Ho, Wo). Corner-wise zero
            # padding like the reference dmcn_im2col_bilinear: weights come
            # from the UNclamped fractional coords, and each corner only
            # contributes if that corner index is inside the map.
            valid = ((ys > -1) & (ys < Hp) & (xs > -1) & (xs < Wp))
            y0f = jnp.floor(ys)
            x0f = jnp.floor(xs)
            wy = (ys - y0f).astype(img.dtype)
            wx = (xs - x0f).astype(img.dtype)
            y0 = y0f.astype(jnp.int32)
            x0 = x0f.astype(jnp.int32)
            y1 = y0 + 1
            x1 = x0 + 1

            def corner(yy, xx):
                ok = (yy >= 0) & (yy < Hp) & (xx >= 0) & (xx < Wp)
                v = img[:, jnp.clip(yy, 0, Hp - 1), jnp.clip(xx, 0, Wp - 1)]
                return v * ok.astype(img.dtype)

            v = (corner(y0, x0) * (1 - wy) * (1 - wx)
                 + corner(y0, x1) * (1 - wy) * wx
                 + corner(y1, x0) * wy * (1 - wx)
                 + corner(y1, x1) * wy * wx)
            return v * valid.astype(img.dtype)

        def per_n(img_n, sy_n, sx_n, m_n):
            # img_n (C,Hp,Wp) -> cols (C, kk, Ho, Wo)
            cols = []
            for dg in range(deformable_groups):
                cols.append(bilinear_nc(
                    img_n[dg * cg:(dg + 1) * cg], sy_n[dg], sx_n[dg]))
            col = jnp.concatenate(cols, axis=0)
            if m_n is not None:
                # m_n (dg, kk, Ho, Wo) -> broadcast over channels in group
                mm = jnp.concatenate([jnp.broadcast_to(
                    m_n[dgi][None], (cg,) + m_n.shape[1:])
                    for dgi in range(deformable_groups)], axis=0)
                col = col * mm
            return col

        if m is not None:
            m = m.reshape(N, deformable_groups, kh * kw, Ho, Wo)
            cols = jax.vmap(per_n)(pad_a, sy, sx, m)
        else:
            cols = jax.vmap(lambda i, y, x_: per_n(i, y, x_, None))(
                pad_a, sy, sx)
        # cols (N, C, kk, Ho, Wo); weight (Cout, C/groups, kh, kw)
        cpg_out = Cout // groups
        outs = []
        for g_ in range(groups):
            cs = cols[:, g_ * Cin_g:(g_ + 1) * Cin_g].reshape(
                N, Cin_g * kh * kw, Ho * Wo)
            wg = w_[g_ * cpg_out:(g_ + 1) * cpg_out].reshape(
                cpg_out, Cin_g * kh * kw)
            outs.append(jnp.einsum("ok,nkp->nop", wg, cs))
        out = jnp.concatenate(outs, axis=1).reshape(N, Cout, Ho, Wo)
        if b_ is not None:
            out = out + b_[None, :, None, None]
        return out

    args = [x, offset, weight] + ([mask] if mask is not None else []) + \
        ([bias] if bias is not None else [])
    return apply_op("deformable_conv", fn, *args)


class DeformConv2D(nn.Layer):
    """Deformable convolution layer (reference vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, ks[0], ks[1]])
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels], is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups,
            mask=mask)


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference vision/ops.py)."""
    rois = np.asarray(fpn_rois._data if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        rn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                        else rois_num)
        img_of_roi = np.repeat(np.arange(rn.shape[0]), rn)
    else:
        rn = None
        img_of_roi = np.zeros(rois.shape[0], dtype=np.int64)
    multi_rois, nums = [], []
    order = []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        order.append(idx)
        multi_rois.append(wrap(jnp.asarray(rois[idx])))
        if rn is not None:
            # per-image counts at this level, shape (num_images,)
            per_img = np.bincount(img_of_roi[idx], minlength=rn.shape[0])
            nums.append(wrap(jnp.asarray(per_img.astype(np.int32))))
        else:
            nums.append(wrap(jnp.asarray([idx.shape[0]], dtype=jnp.int32)))
    cat = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore_ind = np.empty_like(cat)
    restore_ind[cat] = np.arange(cat.shape[0])
    restore = wrap(jnp.asarray(restore_ind.reshape(-1, 1)))
    if rois_num is not None:
        return multi_rois, restore, nums
    return multi_rois, restore


def read_file(filename, name=None):
    """File bytes as a uint8 1-D Tensor (reference vision/ops.py
    read_file over the read_file CPU op)."""
    import numpy as _np

    from .. import to_tensor

    with open(filename, "rb") as f:
        data = f.read()
    return to_tensor(_np.frombuffer(data, _np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference vision/ops.py
    decode_jpeg; nvjpeg there, PIL here — strings/images decode on the
    host, only the pixel tensor crosses to the TPU)."""
    import io as _io

    import numpy as _np
    from PIL import Image

    from .. import to_tensor

    data = bytes(_np.asarray(x._data if hasattr(x, "_data") else x,
                             _np.uint8).tobytes())
    img = Image.open(_io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb",):
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                    # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)       # [C, H, W]
    return to_tensor(arr.copy())


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """NOT IMPLEMENTED — the yolov3_loss op's target-assignment protocol
    (per-anchor responsibility, ignore_thresh objectness masking, label
    smoothing) is not reproduced here yet; raising loudly instead of
    returning silently-wrong losses (pdmodel interop table lists the
    inference-side yolo_box, which IS implemented)."""
    raise NotImplementedError(
        "paddle.vision.ops.yolo_loss is not implemented in paddle_tpu "
        "(training-side YOLOv3 target assignment); yolo_box serving is "
        "supported")


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                      pre_nms_top_n=6000, post_nms_top_n=1000,
                      nms_thresh=0.5, min_size=0.1, eta=1.0,
                      pixel_offset=False, return_rois_num=False,
                      name=None):
    """NOT IMPLEMENTED — RPN proposal generation produces
    variable-length per-image outputs (LoD RpnRois) that do not fit the
    traced executor; raising loudly until an eager padded-output
    implementation lands (distribute_fpn_proposals / roi_align /
    box_coder / nms around it ARE implemented)."""
    raise NotImplementedError(
        "paddle.vision.ops.generate_proposals is not implemented in "
        "paddle_tpu (variable-length RPN outputs); compose box_coder + "
        "nms for a fixed-size proposal path")
