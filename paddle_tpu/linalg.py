"""paddle.linalg namespace (reference: python/paddle/linalg.py — a
re-export facade over tensor.linalg).

Everything tensor.linalg DEFINES is re-exported (the framework's linalg
surface includes completions like vector_norm/matrix_norm/svd_lowrank/
ormqr beyond the reference facade list); internal helpers imported into
that module (Tensor, apply_op, ...) are filtered out by module of
origin so they never become public API the golden gate would bless."""
from .tensor.linalg import *  # noqa: F401,F403
from .tensor import linalg as _tl

__all__ = sorted(
    n for n in dir(_tl)
    if not n.startswith("_") and callable(getattr(_tl, n))
    and getattr(getattr(_tl, n), "__module__", "") == _tl.__name__)
