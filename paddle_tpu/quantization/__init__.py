"""paddle.quantization — PTQ observers + QAT fake-quant.

Reference: /root/reference/python/paddle/quantization/ (config.py
QuantConfig, ptq.py PTQ, qat.py QAT, observers/abs_max.py,
quanters/abs_max.py FakeQuanterWithAbsMaxObserver, wrapper.py).

TPU-native: fake-quant is a pure jax function with a straight-through
estimator (x + stop_gradient(q(x) - x)), so QAT trains through the
rounding inside compiled TrainSteps; PTQ observers collect absmax
statistics eagerly and `convert` bakes scales into quant/dequant pairs.
Simulated int8 (symmetric, per-tensor) — the XLA graph stays in float,
matching the reference's fake-quant semantics.
"""
from __future__ import annotations

import types

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D

__all__ = ["QuantConfig", "PTQ", "QAT", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver", "quanters", "observers"]


def _fake_quant(x, scale, bits=8):
    """Symmetric fake quantization with straight-through gradient."""
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    import jax
    return x + jax.lax.stop_gradient(q - x)


class AbsmaxObserver(Layer):
    """PTQ observer (reference observers/abs_max.py:30): tracks the running
    max(|x|) over calibration batches; scale() = absmax."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._absmax = 0.0
        self._bits = quant_bits

    def forward(self, x):
        a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(a))))
        return x

    def scale(self):
        return self._absmax

    def quant_bits(self):
        return self._bits

    def _instance(self, layer):
        return AbsmaxObserver(self._bits)


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter (reference quanters/abs_max.py:37): moving-average
    absmax scale + fake quant with STE."""

    def __init__(self, moving_rate=0.9, quant_bits=8):
        super().__init__()
        self._rate = moving_rate
        self._bits = quant_bits
        self._scale = None

    def forward(self, x):
        a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        cur = float(jnp.max(jnp.abs(a)))
        if self._scale is None:
            self._scale = cur
        else:
            self._scale = self._rate * self._scale + (1 - self._rate) * cur
        scale = self._scale

        return apply_op("fake_quant",
                        lambda arr: _fake_quant(arr, jnp.asarray(
                            scale, jnp.float32), self._bits), x)

    def scale(self):
        return self._scale

    def _instance(self, layer):
        return FakeQuanterWithAbsMaxObserver(self._rate, self._bits)


class QuantConfig:
    """reference config.py:48 — maps layers/types/names to
    (activation, weight) quanter factories."""

    def __init__(self, activation=None, weight=None):
        self._global = (activation, weight)
        self._by_layer = {}
        self._by_type = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._by_layer[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types_ = (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type])
        for t in types_:
            self._by_type[t] = (activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._by_layer:
            return self._by_layer[id(layer)]
        for t, cfg in self._by_type.items():
            if isinstance(layer, t):
                return cfg
        if isinstance(layer, (Linear, Conv2D)) and any(self._global):
            return self._global
        return None


class _QuantedLayer(Layer):
    """Wrapper executing weight/activation quanters around the wrapped
    layer's forward (reference wrapper.py)."""

    def __init__(self, inner, activation_quanter, weight_quanter):
        super().__init__()
        self._inner = inner
        self._act_q = activation_quanter
        self._w_q = weight_quanter

    def forward(self, x):
        if self._act_q is not None:
            x = self._act_q(x)
        if self._w_q is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            orig = w._data
            qw = self._w_q(w)
            w._data = qw._data if isinstance(qw, Tensor) else qw
            try:
                return self._inner(x)
            finally:
                w._data = orig
        return self._inner(x)

    # expose wrapped params so optimizers keep training them
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_inner"], name)


def _walk_and_wrap(model, config, make):
    wrapped = 0

    def visit(layer):
        nonlocal wrapped
        for name, child in list(layer._sub_layers.items()):
            cfg = config._config_for(child)
            if cfg is not None and not isinstance(child, _QuantedLayer):
                aq = cfg[0]._instance(child) if cfg[0] is not None else None
                wq = cfg[1]._instance(child) if cfg[1] is not None else None
                layer._sub_layers[name] = make(child, aq, wq)
                wrapped += 1
            else:
                visit(child)
    visit(model)
    return wrapped


class QAT:
    """Quantization-aware training (reference qat.py:28)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        _walk_and_wrap(model, self._config, _QuantedLayer)
        return model


class PTQ:
    """Post-training quantization (reference ptq.py:28): quantize() wraps
    with observers; run calibration batches; convert() replaces observers
    with fixed-scale fake-quant."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        _walk_and_wrap(model, self._config, _QuantedLayer)
        return model

    def convert(self, model, inplace=True):
        def visit(layer):
            for name, child in list(layer._sub_layers.items()):
                if isinstance(child, _QuantedLayer):
                    for qn in ("_act_q", "_w_q"):
                        q = child._sub_layers.get(qn)
                        if isinstance(q, AbsmaxObserver):
                            child._sub_layers[qn] = _FixedScaleQuant(
                                q.scale(), q.quant_bits())
                else:
                    visit(child)
        visit(model)
        return model


class _FixedScaleQuant(Layer):
    def __init__(self, scale, bits):
        super().__init__()
        self._scale = float(scale)
        self._bits = bits

    def forward(self, x):
        s = self._scale
        b = self._bits
        return apply_op("quant_dequant",
                       lambda a: _fake_quant(a, jnp.asarray(s, jnp.float32),
                                             b), x)

    def scale(self):
        return self._scale


quanters = types.SimpleNamespace(
    FakeQuanterWithAbsMaxObserver=FakeQuanterWithAbsMaxObserver)
observers = types.SimpleNamespace(AbsmaxObserver=AbsmaxObserver)
