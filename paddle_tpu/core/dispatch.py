"""Eager op dispatch.

The reference's eager hot path is ``*_ad_func → PHI api → KernelFactory →
kernel`` (/root/reference/paddle/fluid/eager/auto_code_generator/generator/
eager_gen.py; SURVEY §3.1). The TPU-native equivalent collapses that chain:
an op is a pure jax function; dispatch (a) unwraps Tensor args to jax arrays,
(b) if autograd is recording, runs the op under ``jax.vjp`` so the pullback +
residuals become the GradNode, (c) wraps outputs. Kernel selection, data
transform, and infermeta are all subsumed by XLA (shape/dtype inference is
jax abstract eval; fusion happens at jit time).
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.flags import flag_value
from . import autograd
from .autograd import GradNode
from .tensor import Tensor


def unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def wrap(arr, stop_gradient=True) -> Tensor:
    t = Tensor(arr, stop_gradient=stop_gradient)
    return t


def _check_nan_inf(name, arrays):
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            # No concrete value under jit tracing — the fused on-device
            # tripwires (observability.numerics, wired into TrainStep and
            # CachedDecoder) own the compiled path.
            continue
        if jnp.issubdtype(a.dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(a)))
            if bad:
                raise FloatingPointError(
                    f"Operator {name} output contains NaN or Inf "
                    f"(FLAGS_check_nan_inf is set)."
                )


def apply_op(name: str, jax_fn: Callable, *args, _outputs_stop_grad=None,
             **static_kwargs) -> Any:
    """Run ``jax_fn`` over mixed Tensor / python args, recording autograd.

    ``static_kwargs`` are compile-time constants. Tensor positional args are
    the differentiable inputs. Returns Tensor or tuple of Tensors mirroring
    ``jax_fn``'s output structure.
    """
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensors = [args[i] for i in tensor_pos]
    arrays = [t._data for t in tensors]

    # AMP O1/O2 autocast at the dispatch boundary (analog of the generated
    # eager_amp_auto_cast.h hooks in the reference).
    from ..amp.auto_cast import amp_state, maybe_autocast_args
    if amp_state() is not None:
        arrays = maybe_autocast_args(name, arrays)

    def f(*arrs):
        full = list(args)
        for p, a in zip(tensor_pos, arrs):
            full[p] = a
        return jax_fn(*full, **static_kwargs)

    # Static-graph mode: execute with placeholder values for shape flow AND
    # record the op into the current Program for compiled replay
    # (the Block.append_op analog; see paddle_tpu/static/program.py).
    from ..static import program as static_program
    if static_program.in_static_mode():
        # f must execute (now, for shape flow; later, under the
        # Executor's jitted replay) WITHOUT re-entering recording: a
        # composite fn (e.g. a to_static jit_program whose first trace
        # happens here) dispatches further ops while it runs, and those
        # belong inside THIS op — appending them to the Program would
        # double-record them and capture trace-time tracers into
        # Program state (Executor.run guards its replay the same way)
        static_program._disable_static()
        try:
            out = f(*arrays)
        finally:
            static_program._enable_static()
        multi_s = isinstance(out, (tuple, list))
        out_leaves_s = list(out) if multi_s else [out]
        wrapped_s = [Tensor(o, stop_gradient=True) for o in out_leaves_s]
        static_program.default_main_program().record(
            name, f, tensors, wrapped_s)
        return tuple(wrapped_s) if multi_s else wrapped_s[0]

    record = autograd.grad_enabled() and any(
        not t.stop_gradient for t in tensors
    )

    hooks = autograd.current_saved_tensors_hooks() if record else None
    if record and hooks is None:
        out, vjp_fn = jax.vjp(f, *arrays)
    else:
        # under saved_tensors_hooks the residual closure is NOT kept —
        # backward rebuilds the vjp from the packed+unpacked snapshot
        out = f(*arrays)
        vjp_fn = None

    multi = isinstance(out, (tuple, list))
    out_leaves = list(out) if multi else [out]

    if flag_value("FLAGS_check_nan_inf"):
        _check_nan_inf(name, [o for o in out_leaves if isinstance(o, jax.Array)])

    if record:
        stored_args = arrays
        if hooks is not None:
            # pack EVERY tensor input of the recorded op (remat-style:
            # backward rebuilds the vjp from these primals) — a
            # documented divergence from the reference, which packs only
            # tensors saved for backward; see the saved_tensors_hooks
            # docstring (core/autograd.py)
            from .tensor import Tensor as _T
            pack, _unpack = hooks
            stored_args = [pack(_T(a, stop_gradient=True))
                           for a in arrays]
        node = GradNode(
            vjp_fn, tensors, n_outputs=len(out_leaves), name=name,
            out_templates=[(o.shape, o.dtype) for o in out_leaves],
            primal_fn=f, primal_args=stored_args, multi_out=multi,
        )
        if hooks is not None:
            import weakref

            node.unpack_fn = hooks[1]

            def _ref(a):
                try:
                    return weakref.ref(a)
                except TypeError:
                    return None
            node.primal_orig_refs = [_ref(a) for a in arrays]
        wrapped = []
        for i, o in enumerate(out_leaves):
            sg = False
            if _outputs_stop_grad is not None and _outputs_stop_grad[i]:
                sg = True
            t = Tensor(o, stop_gradient=sg)
            t._grad_node = node
            t._output_index = i
            t.is_leaf = False
            wrapped.append(t)
    else:
        wrapped = [Tensor(o, stop_gradient=True) for o in out_leaves]

    if multi:
        return tuple(wrapped)
    return wrapped[0]


def defop(name: str, jax_fn: Callable):
    """Build a paddle-shaped op function from a jax function.

    The produced function accepts Tensors/arrays/python scalars positionally
    plus keyword attrs, and ignores the trailing ``name=`` kwarg paddle APIs
    carry.
    """

    op_name = name

    def op(*args, name=None, **kwargs):  # noqa: A002 - paddle API shape
        # `name` here is paddle's user-facing label, NOT the op identity:
        # AMP allow/deny lists key on the registered op name.
        return apply_op(op_name, jax_fn, *args, **kwargs)

    op.__name__ = op_name
    return op
