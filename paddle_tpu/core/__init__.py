from . import autograd, dispatch  # noqa: F401
from .autograd import enable_grad, grad_enabled, no_grad  # noqa: F401
from .dispatch import apply_op, unwrap, wrap  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
