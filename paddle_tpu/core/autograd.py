"""Eager autograd engine.

The reference builds an eager grad graph of ``GradNodeBase`` nodes
(/root/reference/paddle/fluid/eager/grad_node_info.h:168) and runs a
topological queue walk in ``egr::Backward``
(/root/reference/paddle/fluid/eager/backward.cc:380,104). This module is the
TPU-native equivalent: every differentiable op call records a GradNode holding
the ``jax.vjp`` pullback (residuals live on device as jax arrays); backward is
the same in-degree topological walk, with each pullback executing eagerly as
cached XLA ops.
"""
from __future__ import annotations

import contextlib
import threading
from collections import defaultdict, deque
from typing import List, Optional

import jax
import numpy as np

_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set_grad_enabled(v: bool):
    _state.grad_enabled = v


@contextlib.contextmanager
def no_grad():
    prev = grad_enabled()
    _set_grad_enabled(False)
    try:
        yield
    finally:
        _set_grad_enabled(prev)


@contextlib.contextmanager
def enable_grad():
    prev = grad_enabled()
    _set_grad_enabled(True)
    try:
        yield
    finally:
        _set_grad_enabled(prev)


class no_grad_decorator:
    """paddle.no_grad works both as context manager and decorator."""

    def __call__(self, func=None):
        # `paddle.no_grad()` (fresh context manager) and `@paddle.no_grad`
        # (decorator) are both legal in the reference API
        # (/root/reference/python/paddle/fluid/dygraph/base.py `no_grad_`).
        if func is None:
            return no_grad_decorator()
        import functools

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with no_grad():
                return func(*args, **kwargs)

        return wrapper

    def __enter__(self):
        # Stack, not a single slot: paddle.no_grad is a module-level
        # singleton, so nested `with paddle.no_grad:` blocks re-enter the
        # same object and must restore state LIFO.
        if not hasattr(self, "_ctx_stack"):
            self._ctx_stack = []
        ctx = no_grad()
        self._ctx_stack.append(ctx)
        return ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx_stack.pop().__exit__(*exc)


class InputRef:
    """Edge snapshot taken at record time.

    In-place ops rebind ``tensor._grad_node`` after recording (math._inplace),
    so edges must be resolved when the node is CREATED, not when backward
    runs — otherwise an in-place op's node points at itself (the reference
    avoids this with TensorWrapper snapshots,
    /root/reference/paddle/fluid/eager/tensor_wrapper.h).
    """

    __slots__ = ("tensor", "node", "output_index")

    def __init__(self, tensor):
        self.tensor = tensor
        self.node = tensor._grad_node
        self.output_index = tensor._output_index


class GradNode:
    """One recorded differentiable op."""

    __slots__ = (
        "vjp_fn", "input_refs", "n_outputs", "name", "_hooks",
        "out_templates", "primal_fn", "primal_args", "multi_out",
        "unpack_fn", "primal_orig_refs", "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, n_outputs: int, name: str = "op",
                 out_templates=None, primal_fn=None, primal_args=None,
                 multi_out=None):
        self.vjp_fn = vjp_fn
        self.input_refs = [InputRef(t) for t in inputs]
        self.n_outputs = n_outputs
        self.name = name
        self._hooks = None
        # (shape, dtype) per output — used to build zero cotangents for
        # outputs never consumed downstream.
        self.out_templates = out_templates or []
        # Primal op + input-array snapshot. Enables (a) forward-mode JVP
        # over the recorded tape (incubate.autograd.forward_grad) and
        # (b) create_graph=True: backward re-runs jax.vjp(primal_fn)
        # through dispatch so the pullback application is itself recorded
        # (the reference's double-grad nodes, eager/backward.cc:404).
        self.primal_fn = primal_fn
        self.primal_args = primal_args
        # saved_tensors_hooks: when set, primal_args hold PACKED values
        # and unpack_fn restores them on use (backward recomputes the
        # pullback from the unpacked snapshot — remat-style, so pack
        # genuinely controls what stays resident)
        self.unpack_fn = None
        # weakrefs to the ORIGINAL input arrays, kept only for hook
        # nodes (identity-based mutation detection in create_graph)
        self.primal_orig_refs = None
        # Whether the primal returned a tuple/list (a 1-tuple op must get a
        # 1-tuple cotangent — n_outputs alone cannot distinguish it).
        self.multi_out = (n_outputs > 1) if multi_out is None else multi_out

    def primal_values(self):
        """primal_args with any saved_tensors_hooks unpack applied."""
        if self.unpack_fn is None:
            return self.primal_args
        out = []
        for a in self.primal_args:
            v = self.unpack_fn(a)
            out.append(v._data if hasattr(v, "_data") else v)
        return out

    def next_nodes(self):
        return [r.node for r in self.input_refs if r.node is not None]

    def release(self):
        self.vjp_fn = None
        self.input_refs = []
        self.primal_fn = None
        self.primal_args = None


_hooks_state = threading.local()


class saved_tensors_hooks:
    """Context manager transforming what the tape keeps for backward
    (reference python/paddle/autograd/saved_tensors_hooks.py). Ops
    recorded inside store pack_hook(snapshot) INSTEAD of jax's residual
    closure; backward unpacks and REBUILDS the pullback from the
    restored primals (remat-style), so pack genuinely controls resident
    memory — e.g. pack to host numpy for activation offload.

    Divergence from the reference contract: because backward replays the
    whole op from its primals, pack/unpack fire for EVERY recorded op's
    tensor INPUTS — including ops whose vjp needs no residuals — whereas
    the reference invokes the hooks only for tensors actually saved for
    backward. User hooks therefore fire more often (and offload more)
    here; hooks with side effects should be idempotent per tensor."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        stack = getattr(_hooks_state, "stack", None)
        if stack is None:
            stack = _hooks_state.stack = []
        stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_state.stack.pop()
        return False


def current_saved_tensors_hooks():
    stack = getattr(_hooks_state, "stack", None)
    return stack[-1] if stack else None


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _accum(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a + b


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             accumulate_only=None, create_graph: bool = False):
    """Run reverse accumulation from ``tensors`` (paddle.autograd.backward).

    Mirrors RunBackward (/root/reference/paddle/fluid/eager/backward.cc:104):
    build the in-degree map over reachable grad nodes, then process a ready
    queue, accumulating output cotangents per node until all its consumers
    ran. Leaf tensors with ``stop_gradient=False`` receive ``.grad``.

    ``accumulate_only``: optional set of tensor ids — when given, only those
    leaves receive ``.grad`` (used by paddle.grad so unrelated parameters'
    ``.grad`` is never touched).

    ``create_graph``: when True the pullback of every node is re-executed
    through dispatch (``_call_vjp_rerecord``) with the original inputs and
    the cotangents as differentiable Tensors, so the backward computation
    itself records GradNodes — grads carry a graph and can be differentiated
    again (the reference's double-grad nodes, eager/backward.cc:404 +
    generated higher-order *GradNode classes).
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Seed cotangents. In create_graph mode cotangents stay Tensors end to
    # end (so `_accum`'s `a + b` dispatches and is itself recorded).
    node_cots = {}  # id(node) -> list of cotangents per output index
    roots = []
    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if node is None:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}"
                )
            gval = jax.numpy.ones_like(t._data)
            if create_graph:
                gval = Tensor(gval, stop_gradient=True)
        elif create_graph:
            gval = g if isinstance(g, Tensor) else Tensor(
                jax.numpy.asarray(g), stop_gradient=True)
        else:
            gval = g._data if isinstance(g, Tensor) else jax.numpy.asarray(g)
        nid = id(node)
        if nid not in node_cots:
            node_cots[nid] = [None] * node.n_outputs
            roots.append(node)
        node_cots[nid][t._output_index] = _accum(
            node_cots[nid][t._output_index], gval
        )

    # Build in-degree over the reachable graph (number of consumer nodes that
    # will feed cotangents into each node).
    indeg = defaultdict(int)
    seen = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        nid = id(node)
        if nid in seen:
            continue
        seen.add(nid)
        for nxt in node.next_nodes():
            indeg[id(nxt)] += 1
            stack.append(nxt)

    ready = deque(n for n in roots if indeg[id(n)] == 0)
    processed = set()
    while ready:
        node = ready.popleft()
        nid = id(node)
        if nid in processed:
            continue
        processed.add(nid)
        cots = node_cots.pop(nid, None)
        if node.vjp_fn is None and node.primal_fn is None:
            # released node (nodes recorded under saved_tensors_hooks
            # legitimately have vjp_fn None but keep their primal record)
            if cots is not None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time; "
                    "specify retain_graph=True on the first backward."
                )
            continue
        if cots is None:
            # Reachable node that never received a cotangent (its outputs
            # feed only non-differentiable paths): propagate topologically
            # without computing, so downstream in-degrees still drain.
            in_cots = [None] * len(node.input_refs)
        else:
            if create_graph:
                in_cots = _call_vjp_rerecord(node, cots)
            else:
                in_cots = _call_vjp(node, cots)
            if node._hooks:
                for hook in node._hooks:
                    in_cots = hook(in_cots)
        refs = list(node.input_refs)
        for ref, c in zip(refs, in_cots):
            usable = c is not None and not _is_float0(c)
            t = ref.tensor
            nxt = ref.node
            if usable and nxt is not None:
                xid = id(nxt)
                if xid not in node_cots:
                    node_cots[xid] = [None] * nxt.n_outputs
                node_cots[xid][ref.output_index] = _accum(
                    node_cots[xid][ref.output_index], c
                )
            if usable and nxt is None and not t.stop_gradient:
                if accumulate_only is None or id(t) in accumulate_only:
                    _accumulate_leaf_grad(t, c, keep_graph=create_graph)
            if nxt is not None:
                # ALWAYS drain the edge, even for None/float0 cotangents —
                # otherwise nodes with a non-diff consumer never fire.
                xid = id(nxt)
                indeg[xid] -= 1
                if indeg[xid] <= 0:
                    ready.append(nxt)
        if not retain_graph and not create_graph:
            node.release()


def _call_vjp(node, cots):
    """Invoke the stored pullback, substituting zeros for unused outputs.
    Nodes recorded under saved_tensors_hooks store NO pullback closure —
    the vjp is rebuilt here from the unpacked primal snapshot."""
    vjp_fn = node.vjp_fn
    if vjp_fn is None and node.primal_fn is not None:
        _, vjp_fn = jax.vjp(node.primal_fn, *node.primal_values())
    filled = []
    for i, c in enumerate(cots):
        if c is None:
            shape, dtype = node.out_templates[i]
            if jax.numpy.issubdtype(dtype, jax.numpy.inexact):
                c = jax.numpy.zeros(shape, dtype)
            else:
                # Integer/bool outputs take float0 cotangents in jax.
                c = np.zeros(shape, jax.dtypes.float0)
        elif i < len(node.out_templates):
            # jax.vjp requires cotangent dtype == primal output dtype; mixed
            # precision (e.g. fp32 loss-scale times a bf16 autocast output)
            # would otherwise feed a widened cotangent into the pullback.
            _, dtype = node.out_templates[i]
            if (not _is_float0(c) and getattr(c, "dtype", None) != dtype
                    and jax.numpy.issubdtype(dtype, jax.numpy.inexact)):
                c = jax.numpy.asarray(c).astype(dtype)
        filled.append(c)
    if not node.multi_out:
        return vjp_fn(filled[0])
    return vjp_fn(tuple(filled))


def _call_vjp_rerecord(node, cots):
    """create_graph path: rebuild the node's pullback from ``primal_fn`` and
    apply it THROUGH dispatch, with the original input Tensors and the
    cotangent Tensors as differentiable args. The produced grads therefore
    carry GradNodes of their own — including the dependence of the pullback
    on the primal inputs (residuals), which pure pullback-of-cotangent
    differentiation would miss (that term is exactly ∂²L/∂x²)."""
    from .dispatch import apply_op
    from .tensor import Tensor

    if node.primal_fn is None:
        if node.vjp_fn is not None:
            # node exists but was built without a primal (PyLayer / custom
            # ops construct GradNode directly) — name the actual limitation
            raise NotImplementedError(
                f"create_graph=True through op '{node.name}' is not "
                f"supported: its GradNode has no primal record (custom "
                f"PyLayer/op backward). Use jax-transform composition "
                f"(autograd.functional) for higher-order grads of custom "
                f"ops.")
        raise RuntimeError(
            "Trying to backward with create_graph=True through a released "
            "graph; the forward must run with grad enabled in this process."
        )
    n_in = len(node.input_refs)
    # Record-time value snapshots: an in-place op or optimizer step may have
    # rebound tensor._data since the forward (the InputRef/TensorWrapper
    # hazard). When the tensor still holds the recorded array, pass it
    # directly so second-order grads connect to its graph; when mutated,
    # substitute a shadow tensor wrapping the snapshot with the ORIGINAL
    # producer edge, so the pullback evaluates at the correct point.
    from .tensor import Tensor as _T
    primal_vals = node.primal_values()
    primal_tensors = []
    for i, (r, snap) in enumerate(zip(node.input_refs, primal_vals)):
        t = r.tensor
        if node.unpack_fn is not None:
            # hook nodes: identity against the unpacked copy never
            # matches — compare against the recorded original through
            # the weakref kept at record time so second-order graphs
            # stay connected when the tensor was not rebound
            orig = None
            refs = node.primal_orig_refs
            if refs is not None and refs[i] is not None:
                orig = refs[i]()
            mutated = orig is None or t._data is not orig
        else:
            mutated = t._data is not snap
        if mutated:
            t = _T(snap, stop_gradient=r.tensor.stop_gradient)
            t._grad_node = r.node
            t._output_index = r.output_index
            t.is_leaf = r.node is None
        primal_tensors.append(t)
    templates = node.out_templates
    # Output slots that take real (inexact) cotangents; int/bool outputs get
    # static float0 zeros inside the traced bwd fn.
    cot_slots = [i for i, (_, dt) in enumerate(templates)
                 if jax.numpy.issubdtype(dt, jax.numpy.inexact)]
    cot_tensors = []
    for i in cot_slots:
        shape, dtype = templates[i]
        c = cots[i]
        if c is None:
            cot_tensors.append(Tensor(jax.numpy.zeros(shape, dtype),
                                      stop_gradient=True))
        elif isinstance(c, Tensor):
            cot_tensors.append(c)
        else:
            cot_tensors.append(Tensor(jax.numpy.asarray(c),
                                      stop_gradient=True))
    in_dtypes = [getattr(a, "dtype", None) for a in primal_vals]
    keep = [i for i, dt in enumerate(in_dtypes)
            if dt is not None and jax.numpy.issubdtype(dt, jax.numpy.inexact)]
    if not keep:
        return [None] * n_in
    fn = node.primal_fn
    cot_slot_set = set(cot_slots)

    def node_bwd(*args):
        xs = args[:n_in]
        cs = list(args[n_in:])
        out, pull = jax.vjp(fn, *xs)
        multi = isinstance(out, (tuple, list))
        full = []
        k = 0
        for i, (shape, dtype) in enumerate(templates):
            if i in cot_slot_set:
                c = cs[k]
                k += 1
                if c.dtype != dtype:
                    c = c.astype(dtype)
                full.append(c)
            else:
                full.append(np.zeros(shape, jax.dtypes.float0))
        grads = pull(tuple(full) if multi else full[0])
        return tuple(grads[i] for i in keep)

    outs = apply_op(node.name + "_grad", node_bwd,
                    *primal_tensors, *cot_tensors)
    if isinstance(outs, Tensor):
        outs = (outs,)
    in_cots = [None] * n_in
    for j, i in enumerate(keep):
        in_cots[i] = outs[j]
    return in_cots


def _accumulate_leaf_grad(t, cot, keep_graph: bool = False):
    from .tensor import Tensor

    if keep_graph and isinstance(cot, Tensor):
        # create_graph: .grad keeps its GradNode so it can be differentiated
        # again (paddle semantics: grads have grad_fn under create_graph).
        if cot._data.dtype != t._data.dtype and jax.numpy.issubdtype(
                t._data.dtype, jax.numpy.inexact):
            # dispatch-level cast keeps the graph (matches the non-graph
            # branch's dtype contract: .grad has the leaf's dtype)
            from .dispatch import apply_op
            cot = apply_op("cast", lambda a: a.astype(t._data.dtype), cot)
        for h in (t._grad_hooks or []):
            out = h(cot)
            if out is not None:
                cot = out
        if t.grad is None:
            t.grad = cot
            t.grad.name = (t.name or "tensor") + "@GRAD"
        else:
            t.grad = t.grad + cot
        return
    cot = cot._data if isinstance(cot, Tensor) else jax.numpy.asarray(cot)
    if cot.dtype != t._data.dtype and hasattr(cot, "astype"):
        cot = cot.astype(t._data.dtype)
    if t._grad_hooks:
        for h in t._grad_hooks:
            out = h(Tensor(cot, stop_gradient=True))
            if out is not None:
                cot = out._data if isinstance(out, Tensor) else out
    if t.grad is None:
        t.grad = Tensor(cot, stop_gradient=True)
        t.grad.name = (t.name or "tensor") + "@GRAD"
    else:
        t.grad._data = t.grad._data + cot


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad: compute grads of outputs w.r.t. inputs without touching
    ``.grad`` on unrelated leaves (reference GeneralGrad,
    /root/reference/paddle/fluid/eager/general_grad.h)."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        # paddle semantics: retain_graph defaults to create_graph.
        retain_graph = create_graph
    saved = [t.grad for t in inputs]
    saved_stop = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.grad = None
        t.stop_gradient = False
    try:
        backward(outputs, grad_tensors=grad_outputs,
                 retain_graph=bool(retain_graph),
                 accumulate_only={id(t) for t in inputs},
                 create_graph=create_graph)
        results = []
        for t in inputs:
            g = t.grad
            if g is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph. Set allow_unused=True if this "
                    "is desired."
                )
            results.append(g)
        return results
    finally:
        for t, s, ss in zip(inputs, saved, saved_stop):
            t.grad = s
            t.stop_gradient = ss
