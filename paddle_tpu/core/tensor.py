"""The framework Tensor: a paddle-shaped handle over a jax.Array.

The reference's eager Tensor is ``paddle::Tensor``
(/root/reference/paddle/phi/api/include/tensor.h:86) with autograd metadata
(``AutogradMeta``, /root/reference/paddle/fluid/eager/autograd_meta.h:61)
attached by the eager runtime, and Python methods patched on in
/root/reference/paddle/fluid/pybind/eager_method.cc. Here the storage is a
jax.Array (device-resident, async), autograd metadata is a GradNode reference,
and the rich method surface is patched on by paddle_tpu.tensor at import time
— same layering, XLA-native storage.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.device import current_jax_device
from ..framework.place import CPUPlace, Place, TPUPlace
from . import autograd

_tensor_counter = [0]


def _auto_name(prefix="generated_tensor"):
    _tensor_counter[0] += 1
    return f"{prefix}_{_tensor_counter[0]}"


class Tensor:
    __slots__ = (
        "_data", "stop_gradient", "grad", "_grad_node", "_output_index",
        "name", "persistable", "is_leaf", "_grad_hooks", "trainable",
        "__weakref__", "__dict__",
    )

    def __init__(self, data, dtype=None, place: Optional[Place] = None,
                 stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        jdt = dtype_mod.to_jax_dtype(dtype)
        if isinstance(data, jax.ShapeDtypeStruct):
            # Lazy (abstract) tensor: shape/dtype only, no buffer — created
            # under paddle.LazyGuard for AOT planning of configs too big to
            # materialize (reference: fluid/lazy_init.py deferred init).
            self._data = data
        elif isinstance(data, jax.Array):
            if jdt is not None and data.dtype != jdt:
                data = data.astype(jdt)
            self._data = data
        else:
            arr = np.asarray(data)
            if jdt is None and arr.dtype == np.float64:
                # paddle default: python floats / float64 numpy become the
                # default float dtype unless explicitly requested
                if not isinstance(data, np.ndarray) or arr.dtype != np.float64:
                    jdt = dtype_mod.to_jax_dtype(dtype_mod.get_default_dtype())
            dev = place.jax_device() if place is not None else current_jax_device()
            self._data = jax.device_put(
                arr.astype(jdt) if jdt is not None else arr, dev
            )
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._output_index = 0
        self.name = name or _auto_name()
        self.persistable = False
        self.is_leaf = True
        self.trainable = True
        self._grad_hooks = None

    # ---------------- basic properties ----------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> dtype_mod.DType:
        return dtype_mod.convert_dtype(self._data.dtype)

    @property
    def place(self) -> Place:
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return CPUPlace()
        if dev.platform.lower() == "cpu":
            return CPUPlace()
        return TPUPlace(dev.id)

    @property
    def T(self):
        from ..tensor import linalg
        return linalg.transpose_last2(self) if self.ndim >= 2 else self

    def numel(self):
        return self.size

    # ---------------- conversion ----------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is "
                "ambiguous."
            )
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}{grad_s},\n       {np.asarray(self._data)})"
        )

    # ---------------- autograd ----------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor] if grad_tensor is not None else None,
                          retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..core.dispatch import apply_op
        return apply_op("clone", lambda x: x + 0, self)

    def register_hook(self, hook):
        """Hook on this tensor's accumulated leaf gradient."""
        if self._grad_hooks is None:
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(handle_self):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # ---------------- placement ----------------
    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, Place)):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            if isinstance(device, str):
                from ..framework.device import set_device, device_guard
                with device_guard(device):
                    dev = current_jax_device()
            else:
                dev = device.jax_device()
            out = Tensor(jax.device_put(out._data, dev),
                         stop_gradient=out.stop_gradient)
        return out

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # compat: lands on the accelerator
        return self.to("tpu")

    def tpu(self, *a, **k):
        return self.to("tpu")

    # ---------------- mutation ----------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        arr = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            arr = arr.reshape(self._data.shape)
        self._data = jax.device_put(arr, list(self._data.devices())[0])
        return self

    def copy_(self, other, *args):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        return self.fill_(0)

    def _bump_version(self):
        pass

    # block_until_ready passthrough for benchmarking
    def block_until_ready(self):
        jax.block_until_ready(self._data)
        return self

    # value semantics helpers used by optimizers (functional update)
    def _replace_data(self, new_data):
        self._data = new_data
        return self


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    if isinstance(data, Tensor) and dtype is None and place is None:
        t = Tensor(data._data, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """A trainable leaf tensor (reference: paddle.fluid.framework.Parameter)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, name=name or _auto_name("param"),
                         stop_gradient=not trainable)
        self.persistable = True
        self.trainable = trainable
