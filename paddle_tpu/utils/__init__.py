"""paddle.utils equivalent (reference __all__: deprecated, run_check,
require_version, try_import — python/paddle/utils/__init__.py:59)."""
from . import cpp_extension  # noqa: F401
from . import download  # noqa: F401
from .cpp_extension import custom_op  # noqa: F401

__all__ = ["deprecated", "run_check", "require_version", "try_import"]


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def deprecated(update_to="", since="", reason="", level=0):
    """Mark an API deprecated (reference utils/deprecated.py): warns at
    level<2 (filter forced open so the warning is actually visible,
    as the reference does), raises at level 2."""
    import functools
    import warnings

    def decorator(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            warnings.simplefilter("always", DeprecationWarning)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def require_version(min_version, max_version=None):
    """Check the installed framework version against [min, max]
    (reference utils/__init__.py require_version)."""
    import paddle_tpu

    def parse(v):
        parts = [int(x) for x in str(v).split(".")[:3] if x.isdigit()]
        return tuple(parts + [0] * (3 - len(parts)))   # zero-pad: 0.1 == 0.1.0

    cur_str = getattr(paddle_tpu, "__version__", "0.0.0")
    cur = parse(cur_str)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {cur_str} < required minimum {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {cur_str} > required maximum {max_version}")
    return True


def run_check():
    """Install sanity check (reference utils/install_check.py:232): run
    a tiny compiled train step on the default device, and when several
    devices are visible, a psum across all of them — then report."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    kind = devs[0].platform
    print(f"Running verify on 1 {kind} device.")
    a = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
    out = jax.jit(lambda x: (x @ x.T).sum())(a)
    if not bool(jnp.isfinite(out)):   # not assert: must survive python -O
        raise RuntimeError("single-device compiled matmul failed")
    print(f"PaddleTPU works well on 1 {kind}.")
    if len(devs) > 1:
        n = len(devs)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(devs), ("d",))
        x = jax.device_put(jnp.ones((n, 4)),
                           NamedSharding(mesh, P("d", None)))
        total = jax.jit(lambda v: v.sum())(x)
        if float(total) != n * 4.0:
            raise RuntimeError("multi-device reduction failed")
        print(f"PaddleTPU works well on {n} {kind}s.")
    print("PaddleTPU is installed successfully! Let's start deep "
          "learning with PaddleTPU now.")
