"""paddle.utils equivalent."""
from . import cpp_extension  # noqa: F401
from . import download  # noqa: F401
from .cpp_extension import custom_op  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None
