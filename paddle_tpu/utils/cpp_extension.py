"""Custom-op toolchain — paddle.utils.cpp_extension parity.

Reference: /root/reference/python/paddle/utils/cpp_extension/
cpp_extension.py (setup :79, CppExtension :239, CUDAExtension :289, jit
load :800) compiling user C++/CUDA against paddle/extension.h and
registering via PD_BUILD_OP.

TPU-native split:
- device compute customization = Pallas kernels + jax.custom_vjp,
  registered through :func:`paddle_tpu.utils.custom_op` below (the analog
  of PD_BUILD_OP for the compiled path).
- host-side native code (data feeding, IO, runtime glue) = plain C/C++
  compiled by :func:`load` into a shared library reachable over ctypes
  (no pybind11 in this environment; the C ABI is the binding layer, same
  design as paddle_tpu/native).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence


def _default_build_dir():
    d = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Build spec (reference cpp_extension.py:239)."""

    def __init__(self, sources: Sequence[str], name: Optional[str] = None,
                 extra_compile_args: Optional[List[str]] = None,
                 extra_link_args: Optional[List[str]] = None, **kw):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])


def CUDAExtension(sources, *args, **kwargs):  # noqa: N802 — API parity
    """Accepted for parity; on TPU hosts device code is Pallas, so this
    builds the host-side sources exactly like CppExtension."""
    return CppExtension(sources, *args, **kwargs)


def load(name: str, sources: Sequence[str],
         extra_cxx_cflags: Optional[List[str]] = None,
         extra_ldflags: Optional[List[str]] = None,
         build_directory: Optional[str] = None, verbose: bool = False,
         **kw) -> ctypes.CDLL:
    """JIT-compile C++ sources into a shared library and dlopen it
    (reference cpp_extension.py:800). Rebuilds only when the source
    content hash changes."""
    build_dir = build_directory or _default_build_dir()
    blobs = []
    for s in sources:
        with open(s, "rb") as f:
            blobs.append(f.read())
    tag = hashlib.sha256(b"\0".join(blobs)
                         + " ".join(extra_cxx_cflags or []).encode()
                         ).hexdigest()[:16]
    so_path = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *(extra_cxx_cflags or []), *sources, "-o", so_path,
               *(extra_ldflags or [])]
        if verbose:
            print(" ".join(cmd))
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{res.stderr[-4000:]}")
    return ctypes.CDLL(so_path)


def setup(name=None, ext_modules=None, **kw):
    """Eager build of the given extensions (the reference's setuptools
    path); returns {ext_name: CDLL}."""
    out = {}
    for ext in ext_modules or []:
        ext_name = ext.name or name or "paddle_tpu_ext"
        out[ext_name] = load(ext_name, ext.sources,
                             extra_cxx_cflags=ext.extra_compile_args,
                             extra_ldflags=ext.extra_link_args)
    return out


def custom_op(name: str, backward: Optional[Callable] = None):
    """Decorator registering a custom COMPILED op (the PD_BUILD_OP analog
    for the XLA path): wraps a jax-traceable function — typically a Pallas
    kernel — as a framework op with optional custom VJP, callable on
    Tensors in eager and traced mode.

        @custom_op("my_scale", backward=lambda res, g: (g * 2.0,))
        def my_scale(x):
            return x * 2.0
    """
    import jax

    from ..core.dispatch import apply_op

    def deco(fn):
        run = fn
        if backward is not None:
            run = jax.custom_vjp(fn)
            run.defvjp(lambda *args: (fn(*args), args),
                       backward)

        def op(*tensors, **kwargs):
            return apply_op(name, run, *tensors, **kwargs)

        op.__name__ = name
        op.__wrapped__ = fn
        return op

    return deco
