"""paddle.utils.download — local-file resolution (no-egress environment).

Reference: python/paddle/utils/download.py get_path_from_url downloads and
caches archives; this environment has no network, so the equivalent
surface resolves local paths and raises a uniform, actionable error when
an archive is absent.
"""
from __future__ import annotations

import os

__all__ = ["require_local_file", "get_path_from_url"]


def require_local_file(path, what):
    """Return ``path`` if it exists, else raise the standard no-egress
    error used by every dataset loader."""
    if path is None or not os.path.exists(path):
        raise ValueError(
            f"{what}: file {path!r} not found. This environment has no "
            "network egress; download the archive elsewhere and pass its "
            "local path (the reference would auto-download here).")
    return path


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    """Reference-compatible name: resolves an already-downloaded archive
    under ``root_dir``; never downloads."""
    fname = os.path.join(root_dir, os.path.basename(url))
    return require_local_file(fname, f"archive for {url}")
