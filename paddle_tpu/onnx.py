"""paddle.onnx — export shim.

Reference: python/paddle/onnx/export.py delegates to the external
paddle2onnx package. TPU-native stance: the portable serving artifact is
the StableHLO pdmodel (framework/exporting.py) — `paddle.onnx.export`
writes that artifact (same layer, same inputs contract) and raises a
clear error for the actual .onnx protobuf conversion, which needs the
external converter the reference also requires.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` as a servable artifact at ``path``.

    Writes the StableHLO pdmodel/pdiparams pair (loadable with
    paddle_tpu.jit.load / inference.create_predictor). A true ONNX
    protobuf requires the external paddle2onnx-equivalent converter —
    not available offline — so requesting a literal .onnx file raises.
    """
    if str(path).endswith(".onnx"):
        raise NotImplementedError(
            "literal ONNX protobuf export requires the external "
            "paddle2onnx converter (the reference shells out to it too); "
            "use the StableHLO artifact (paddle_tpu.jit.save / this "
            "function without the .onnx suffix) for portable serving")
    from .jit.api import save as jit_save
    jit_save(layer, str(path), input_spec=input_spec)
    return str(path)
