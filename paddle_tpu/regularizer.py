"""Weight regularizers (reference: /root/reference/python/paddle/fluid/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def apply(self, grad_arr, param_arr):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def apply(self, grad_arr, param_arr):
        return grad_arr + self.coeff * param_arr

    def __repr__(self):
        return f"L2Decay({self.coeff})"


class L1Decay(WeightDecayRegularizer):
    def apply(self, grad_arr, param_arr):
        import jax.numpy as jnp
        return grad_arr + self.coeff * jnp.sign(param_arr)

    def __repr__(self):
        return f"L1Decay({self.coeff})"
