"""DataLoader.

Reference: /root/reference/python/paddle/fluid/reader.py:311 +
fluid/dataloader/dataloader_iter.py:162,370 (single/multiprocess iterators,
shared-memory transport, async device transfer). TPU-native equivalent:
multiprocessing workers feed host numpy batches through a queue; the main
process overlaps host→HBM transfer (jax.device_put is async) with a small
prefetch depth, which is the TPU analog of pin_memory+cuda streams.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import traceback

import numpy as np

from ..core.tensor import Tensor
from .dataset import BatchSampler, IterableDataset


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    return batch


def _to_tensor_tree(batch, return_list=True):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return [_to_tensor_tree(b) for b in batch]
    if isinstance(batch, dict):
        return {k: _to_tensor_tree(v) for k, v in batch.items()}
    return batch


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, seed, ring_name=None):
    global _worker_info
    _worker_info = _WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) % (2 ** 31))
    ring = None
    if ring_name is not None:
        # shared-memory batch transport (csrc/shm_ring.cc): payload rides
        # the per-worker shm ring, the queue carries only control tuples
        try:
            from ..native.shm_ring import ShmRing
            ring = ShmRing(ring_name, owner=False)
        except Exception:  # pragma: no cover — fall back to queue payload
            ring = None
    import pickle
    while True:
        task = index_queue.get()
        if task is None:
            break
        batch_id, indices = task
        try:
            samples = [dataset[i] for i in indices]
            data = collate_fn(samples)
            if ring is not None:
                payload = pickle.dumps(data, protocol=5)
                if len(payload) <= ring.payload_capacity and \
                        ring.push(payload):
                    data_queue.put((batch_id, (_SHM_SENTINEL, worker_id),
                                    None))
                    continue
            data_queue.put((batch_id, data, None))
        except Exception:  # pragma: no cover
            data_queue.put((batch_id, None, traceback.format_exc()))


_SHM_SENTINEL = "__shm__"


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = batch_size

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield _to_tensor_tree(self.collate_fn(batch))
                batch = []
        if batch:
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_single(self):
        for indices in self.batch_sampler:
            samples = [self.dataset[i] for i in indices]
            yield _to_tensor_tree(self.collate_fn(samples))

    def _iter_multiprocess(self):
        import os
        import pickle

        ctx = mp.get_context("fork")
        index_queues = []
        data_queue = ctx.Queue()
        workers = []
        rings = []
        seed = np.random.randint(0, 2 ** 31)
        use_shm = self.use_shared_memory
        if use_shm:
            from ..native.shm_ring import ShmRing, available
            use_shm = available()
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            ring_name = None
            ring = None
            if use_shm:
                ring_name = f"/pt_dl_{os.getpid()}_{id(self)}_{wid}"
                try:
                    ring = ShmRing(ring_name, owner=True)
                except Exception:
                    ring_name, ring = None, None
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, iq, data_queue, self.collate_fn, wid,
                      self.num_workers, seed, ring_name),
                daemon=True)
            w.start()
            workers.append(w)
            index_queues.append(iq)
            rings.append(ring)
        self._shm_batches = 0

        def _resolve(data):
            if isinstance(data, tuple) and len(data) == 2 and \
                    data[0] == _SHM_SENTINEL:
                self._shm_batches += 1
                return pickle.loads(rings[data[1]].pop(timeout_ms=60000))
            return data

        try:
            sampler_iter = iter(self.batch_sampler)
            batch_id = 0
            sent = 0
            reorder = {}
            next_yield = 0
            # pre-fill
            for _ in range(self.prefetch_factor * self.num_workers):
                try:
                    indices = next(sampler_iter)
                except StopIteration:
                    break
                index_queues[sent % self.num_workers].put((batch_id, indices))
                batch_id += 1
                sent += 1

            done = 0
            total = len(self.batch_sampler)
            while next_yield < total:
                if next_yield in reorder:
                    data = reorder.pop(next_yield)
                    yield _to_tensor_tree(data)
                    next_yield += 1
                    continue
                bid, data, err = data_queue.get(
                    timeout=self.timeout if self.timeout else None)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed:\n{err}")
                # pop the shm payload NOW (in control-message order) — the
                # per-worker ring is FIFO, so deferring pops to yield order
                # would pair payloads with the wrong batch ids
                data = _resolve(data)
                try:
                    indices = next(sampler_iter)
                    index_queues[sent % self.num_workers].put(
                        (batch_id, indices))
                    batch_id += 1
                    sent += 1
                except StopIteration:
                    pass
                reorder[bid] = data
        finally:
            for iq in index_queues:
                iq.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            for r in rings:
                if r is not None:
                    r.close()
                    r.free()
