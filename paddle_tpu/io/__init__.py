from .dataloader import DataLoader, get_worker_info  # noqa: F401
from .dataset import (  # noqa: F401
    BatchSampler, ChainDataset, ComposeDataset, ConcatDataset, Dataset,
    DistributedBatchSampler, IterableDataset, RandomSampler, Sampler,
    WeightedRandomSampler,
    SequenceSampler, Subset, TensorDataset, random_split,
)
