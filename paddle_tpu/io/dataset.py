"""Datasets & samplers (reference: /root/reference/python/paddle/io/ and
python/paddle/fluid/dataloader/)."""
from __future__ import annotations

import bisect
import math

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, tuple):
                sample.extend(item)
            else:
                sample.append(item)
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            for sample in d:
                yield sample


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        if ds_idx > 0:
            idx -= self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("Sum of input lengths does not equal dataset length")
    indices = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for ln in lengths:
        out.append(Subset(dataset, indices[offset:offset + ln]))
        offset += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    """Indices drawn with probability proportional to ``weights``
    (reference io/sampler.py WeightedRandomSampler)."""

    def __init__(self, weights, num_samples, replacement=True):
        w = np.asarray(weights, np.float64)
        if w.ndim != 1 or (w < 0).any():
            raise ValueError("weights must be a 1-D non-negative list")
        if w.sum() == 0:
            raise ValueError("weights sum to zero — no index can be "
                             "drawn")
        if not replacement and num_samples > w.size:
            raise ValueError(
                "num_samples cannot exceed len(weights) when drawing "
                "without replacement")
        self.weights = w
        self.num_samples = int(num_samples)
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler
    (reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            from ..distributed import get_world_size
            num_replicas = get_world_size()
        if rank is None:
            from ..distributed import get_rank
            rank = get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
