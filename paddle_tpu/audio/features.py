"""audio.features — Spectrogram / MelSpectrogram / LogMelSpectrogram /
MFCC layers (reference: audio/features/layers.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..nn.layer.layers import Layer
from . import functional as AF


def _frame(x, frame_length, hop_length):
    """[..., T] -> [..., n_frames, frame_length] via strided gather."""
    n = (x.shape[-1] - frame_length) // hop_length + 1
    starts = jnp.arange(n) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]


def _stft_power(x, n_fft, hop_length, win, power, center,
                pad_mode="reflect"):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frame(x, n_fft, hop_length) * win
    spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
    mag = jnp.abs(spec)
    out = mag if power == 1.0 else mag ** power
    return jnp.swapaxes(out, -1, -2)  # [..., n_freqs, n_frames]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        wl = win_length or n_fft
        w = AF.get_window(window, wl, dtype=dtype)._data
        if wl < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        self._win = w

    def forward(self, x):
        cfg = dict(n_fft=self.n_fft, hop=self.hop_length, power=self.power,
                   center=self.center)
        win = self._win
        pm = self.pad_mode
        return apply_op(
            "spectrogram",
            lambda a: _stft_power(a, cfg["n_fft"], cfg["hop"], win,
                                  cfg["power"], cfg["center"], pm), x)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype=dtype)
        self._fbank = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)._data

    def forward(self, x):
        spec = self.spectrogram(x)
        fb = self._fbank
        return apply_op("mel_spectrogram",
                        lambda s: jnp.einsum("mf,...ft->...mt", fb, s),
                        spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self._dct = AF.create_dct(n_mfcc, n_mels, dtype=dtype)._data

    def forward(self, x):
        lm = self.log_mel(x)
        dct = self._dct
        return apply_op("mfcc",
                        lambda s: jnp.einsum("mk,...mt->...kt", dct, s), lm)
