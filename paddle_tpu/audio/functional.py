"""audio.functional — windows, mel filterbanks, dB conversion.

Reference: /root/reference/python/paddle/audio/functional/
(window.py get_window, functional.py hz_to_mel/mel_to_hz/
compute_fbank_matrix/power_to_db, create_dct).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import wrap

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct"]


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Hann/Hamming/Blackman/Kaiser/identity windows (reference
    window.py:286 get_window)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length
    m = n if fftbins else n - 1
    k = np.arange(n)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / max(m, 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / max(m, 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / max(m, 1))
             + 0.08 * np.cos(4 * np.pi * k / max(m, 1)))
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = np.kaiser(n, beta)
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return wrap(jnp.asarray(w, jnp.dtype(dtype)))


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz)
                    / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    return mel_to_hz(np.linspace(hz_to_mel(f_min, htk),
                                 hz_to_mel(f_max, htk), n_mels), htk)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, n_fft//2+1] triangular mel filterbank (reference
    functional.py:185)."""
    f_max = f_max if f_max is not None else sr / 2.0
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2.0, n_freqs)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return wrap(jnp.asarray(weights, jnp.dtype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(S/ref) with clamping (reference functional.py:312)."""
    x = spect._data if hasattr(spect, "_data") else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return wrap(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference functional.py:344)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return wrap(jnp.asarray(basis, jnp.dtype(dtype)))
