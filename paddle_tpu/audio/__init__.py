"""paddle.audio — spectral feature layers + functional windows/mels.

Reference: /root/reference/python/paddle/audio/ (features/layers.py
Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC over functional/
window.py get_window + functional.py compute_fbank_matrix, backed by
paddle's fft ops). TPU-native: framing is a strided gather and the STFT
is jnp.fft — everything jits and fuses on the accelerator.
"""
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import functional  # noqa: F401
from .features import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram,
)
