"""paddle.audio.backends — wav IO via the stdlib wave module.

Reference: python/paddle/audio/backends/wave_backend.py (info/load/save
:37/:89/:168) with optional soundfile backend. Only the wave backend is
shipped (soundfile isn't in this image); PCM 8/16/32-bit wavs round-trip.
"""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable: only the stdlib wave "
            "backend is shipped (soundfile is not in this environment)")


_WIDTH_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def info(filepath):
    with _wave.open(str(filepath), "rb") as f:
        return AudioInfo(
            sample_rate=f.getframerate(),
            num_samples=f.getnframes(),
            num_channels=f.getnchannels(),
            bits_per_sample=f.getsampwidth() * 8,
            encoding=f"PCM_{'U' if f.getsampwidth() == 1 else 'S'}",
        )


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (Tensor [C, N] (channels_first) or [N, C], sample_rate).

    normalize=True maps PCM ints to float32 in [-1, 1] (as the reference
    wave backend does); normalize=False returns raw integer samples.
    """
    import paddle_tpu as paddle
    with _wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        if width not in _WIDTH_DTYPE:
            raise ValueError(f"unsupported sample width {width}")
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    data = np.frombuffer(raw, dtype=_WIDTH_DTYPE[width]).reshape(-1, nch)
    if normalize:
        if width == 1:  # unsigned 8-bit
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    out = data.T if channels_first else data
    return paddle.to_tensor(np.ascontiguousarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True, encoding=None,
         bits_per_sample=16):
    """Write a float (-1..1) or integer tensor as PCM wav."""
    if bits_per_sample not in (8, 16, 32):
        raise ValueError("bits_per_sample must be 8, 16 or 32")
    arr = src.numpy() if hasattr(src, "numpy") else np.asarray(src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T  # -> [N, C]
    if not np.issubdtype(arr.dtype, np.floating):
        # normalize integer input to float first so any source width can
        # be re-encoded at the requested bits_per_sample
        if arr.dtype == np.uint8:
            arr = (arr.astype(np.float32) - 128.0) / 128.0
        else:
            src_bits = arr.dtype.itemsize * 8
            arr = arr.astype(np.float32) / float(2 ** (src_bits - 1))
    if bits_per_sample == 8:
        arr = ((arr * 127.0) + 128.0).clip(0, 255).astype(np.uint8)
    else:
        scale = float(2 ** (bits_per_sample - 1) - 1)
        dt = np.int16 if bits_per_sample == 16 else np.int32
        arr = (arr * scale).clip(-scale - 1, scale).astype(dt)
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(bits_per_sample // 8)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())
