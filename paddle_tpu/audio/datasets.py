"""paddle.audio.datasets — TESS / ESC50 audio-classification datasets.

Reference: python/paddle/audio/datasets/{dataset,tess,esc50}.py. The
reference downloads archives; here the classes scan a local directory of
wav files (``data_dir=``) laid out like the extracted archives, with
feature extraction (raw/melspectrogram/mfcc/spectrogram/logmelspectrogram)
shared through AudioClassificationDataset exactly as the reference does.
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset
from ..utils.download import require_local_file
from . import features as _features
from .backends import load as _load_wav

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]


_FEAT = {
    "raw": None,
    "melspectrogram": "MelSpectrogram",
    "mfcc": "MFCC",
    "logmelspectrogram": "LogMelSpectrogram",
    "spectrogram": "Spectrogram",
}


class AudioClassificationDataset(Dataset):
    """(wav file, label) list + on-the-fly feature extraction
    (reference: audio/datasets/dataset.py)."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        if feat_type not in _FEAT:
            raise ValueError(
                f"unknown feat_type {feat_type!r}; one of {sorted(_FEAT)}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._extractor = None

    def _extract(self, waveform):
        import paddle_tpu as paddle
        if self.feat_type == "raw":
            return waveform
        if self._extractor is None:
            cls = getattr(_features, _FEAT[self.feat_type])
            cfg = dict(self.feat_config)
            if self.sample_rate is not None:
                cfg.setdefault("sr", self.sample_rate)
            self._extractor = cls(**cfg)
        return self._extractor(paddle.to_tensor(waveform))

    def __getitem__(self, idx):
        wav, sr = _load_wav(self.files[idx])
        mono = wav.numpy().mean(axis=0).astype(np.float32)
        feat = self._extract(mono)
        if not isinstance(feat, np.ndarray):
            feat = np.asarray(feat.numpy())
        return feat, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.files)


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set: 7 emotions encoded in filenames
    (reference: tess.py). data_dir: directory containing the extracted
    ``*_<emotion>.wav`` files (searched recursively)."""

    labels_list = ["angry", "disgust", "fear", "happy", "neutral",
                   "ps", "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, archive=None, **kwargs):
        if not (isinstance(n_folds, int) and 1 <= split <= n_folds):
            raise ValueError("require 1 <= split <= n_folds")
        data_dir = require_local_file(data_dir, "TESS data directory")
        wavs = []
        for root, _, names in sorted(os.walk(data_dir)):
            for nm in sorted(names):
                if nm.lower().endswith(".wav"):
                    wavs.append(os.path.join(root, nm))
        files, labels = [], []
        for i, w in enumerate(wavs):
            emotion = os.path.basename(w).rsplit(".", 1)[0] \
                .split("_")[-1].lower()
            if emotion not in self.labels_list:
                continue
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(w)
                labels.append(self.labels_list.index(emotion))
        super().__init__(files, labels, feat_type=feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds: 50 classes, fold encoded in the
    filename ``<fold>-<src>-<take>-<target>.wav`` (reference: esc50.py)."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, archive=None, **kwargs):
        data_dir = require_local_file(data_dir, "ESC-50 audio directory")
        files, labels = [], []
        for root, _, names in sorted(os.walk(data_dir)):
            for nm in sorted(names):
                if not nm.lower().endswith(".wav"):
                    continue
                parts = nm.rsplit(".", 1)[0].split("-")
                if len(parts) != 4 or not (parts[0].isdigit()
                                           and parts[3].isdigit()):
                    continue  # skip non-conforming filenames (readmes etc.)
                fold, target = int(parts[0]), int(parts[3])
                keep = (fold != split) if mode == "train" \
                    else (fold == split)
                if keep:
                    files.append(os.path.join(root, nm))
                    labels.append(target)
        super().__init__(files, labels, feat_type=feat_type, **kwargs)
