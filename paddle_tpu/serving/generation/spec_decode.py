"""Host-side speculative-decoding accept/resample (Leviathan et al.).

The engine's verify step hands this module, per lane, the target
model's logits over the ``[last_accepted, d_1..d_k]`` window
(``logits[i]`` is the target distribution for the token at position
``ctx + i + 1`` — the slot proposal ``d_{i+1}`` wants to fill) plus
the draft's proposed tokens and, for sampled requests, the draft
distributions they were drawn from. ``accept_tokens`` walks the
proposals left to right:

- **greedy** (temperature 0): a proposal is accepted iff it equals the
  target argmax; the first mismatch emits the target argmax instead
  and stops. The emitted stream is therefore EXACTLY the
  non-speculative greedy stream.
- **sampled**: standard accept-and-resample — accept ``d`` with
  probability ``min(1, p_t(d) / p_d(d))``; on rejection sample from
  the residual ``normalize(max(p_t - p_d, 0))`` and stop. The marginal
  distribution of every emitted token equals plain temperature
  sampling from the target model (the Leviathan et al. identity), so
  speculation changes latency, never the output law.

When every proposal survives, one BONUS token is selected from the
final window position's logits — the step that makes a fully-accepted
round emit ``k + 1`` tokens.

Pure numpy, no engine state: unit-testable for the distribution
identity in isolation.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["accept_tokens", "softmax"]


def softmax(logits: np.ndarray, temperature: float) -> np.ndarray:
    z = np.asarray(logits, np.float64) / float(temperature)
    z -= z.max(-1, keepdims=True)
    p = np.exp(z)
    return p / p.sum(-1, keepdims=True)


def _sample(p: np.ndarray, u: float) -> int:
    cdf = np.cumsum(p)
    return int(min(np.searchsorted(cdf, u * cdf[-1], side="right"),
                   p.shape[-1] - 1))


def accept_tokens(target_logits: np.ndarray,
                  draft_tokens: np.ndarray,
                  draft_probs: Optional[np.ndarray],
                  temperature: float,
                  rng: np.random.RandomState,
                  max_emit: int,
                  eos_token_id: Optional[int] = None
                  ) -> Tuple[List[int], int]:
    """Judge one lane's proposals against one verify window.

    target_logits: [k+1, vocab] — row ``i`` scores the token at window
    offset ``i + 1``; row ``k`` is the bonus position. draft_tokens:
    [k] proposed ids. draft_probs: [k, vocab] draft distributions
    (required when temperature > 0; ignored for greedy). ``max_emit``
    caps emissions to the lane's remaining token/page budget; hitting
    it (or ``eos_token_id``) stops the walk early.

    Returns ``(emitted_tokens, n_draft_accepted)`` —
    ``n_draft_accepted`` counts accepted PROPOSALS only (the
    acceptance-rate numerator; the bonus/resample token is excluded).
    """
    k = int(draft_tokens.shape[0])
    greedy = float(temperature) <= 0.0
    emitted: List[int] = []
    accepted = 0

    def stop(tok: int) -> bool:
        return (eos_token_id is not None and tok == eos_token_id) \
            or len(emitted) >= max_emit

    for i in range(k):
        if len(emitted) >= max_emit:
            return emitted, accepted
        d = int(draft_tokens[i])
        if greedy:
            t = int(np.asarray(target_logits[i]).argmax())
            if t == d:
                emitted.append(d)
                accepted += 1
                if stop(d):
                    return emitted, accepted
                continue
            emitted.append(t)        # greedy "resample": the argmax
            return emitted, accepted
        pt = softmax(target_logits[i], temperature)
        pd = np.asarray(draft_probs[i], np.float64)
        ratio = pt[d] / max(pd[d], 1e-300)
        if rng.random_sample() < min(1.0, ratio):
            emitted.append(d)
            accepted += 1
            if stop(d):
                return emitted, accepted
            continue
        residual = np.maximum(pt - pd, 0.0)
        total = residual.sum()
        if total <= 0.0:             # pt == pd exactly: resample pt
            residual, total = pt, 1.0
        emitted.append(_sample(residual / total, rng.random_sample()))
        return emitted, accepted

    # every proposal accepted: the bonus token from the final position
    if len(emitted) < max_emit:
        if greedy:
            emitted.append(int(np.asarray(target_logits[k]).argmax()))
        else:
            emitted.append(_sample(softmax(target_logits[k], temperature),
                                   rng.random_sample()))
    return emitted, accepted
