"""paddle_tpu.serving.generation — autoregressive decode serving.

The generation analog of the batch-predict ``InferenceServer``:
prefill/decode split over a paged KV cache (PagedAttention layout) with
Orca-style continuous batching — sequences join and leave the in-flight
decode batch every iteration, the decode step compiles ONCE at
``[max_batch, 1]`` with dead lanes slot-masked, and tokens stream back
through ``StreamingFuture``.

Pieces:

- ``GenerationServer`` (engine.py): ``submit_generate(prompt,
  max_new_tokens, temperature) -> StreamingFuture`` with the serving
  layer's backpressure/deadline semantics, continuous batcher worker,
  ``paddle_decode_*`` metrics on the observability registry, warmup +
  warmup-manifest replay over the decode lattice.
- ``CachedDecoder`` (model_fns.py): the two jitted device entry points
  (bucketed prefill, fixed-shape decode) over a cache-capable model,
  KV pools donated where the backend supports it, persistent-compile-
  cache AOT tier first.
- ``PagedKVCache`` (kv_cache.py): preallocated per-layer
  ``[num_pages, page_size, heads, head_dim]`` pools + the host page
  allocator (page 0 reserved as the trash page for masked writes),
  REFCOUNTED so full pages can be shared across sequences.
- ``PrefixCache`` (prefix_cache.py): radix index over immutable full
  KV pages keyed by token content — shared-prefix reuse with
  copy-on-write at the divergence page and LRU eviction under pool
  pressure.
- ``accept_tokens`` (spec_decode.py): host-side accept-and-resample
  for speculative decoding (draft proposes k, the target verifies all
  k in one fixed-shape step; output distribution unchanged).
- ``sample_next_tokens`` (sampling.py): vectorized host-side
  greedy/temperature selection, shared with
  ``HybridParallelInferenceHelper.generate``.

Model contract: ``forward(ids, cache=...)`` returning ``(logits,
(k', v'))`` plus ``init_kv_pools``/``kv_cache_spec`` — implemented by
``models.GPTForCausalLM`` (module and stacked decoders); see
``models.gpt.GPTKVCache`` for the threaded pytree.

Knobs: ``FLAGS_decode_*`` in framework/flags.py.
"""
from __future__ import annotations

from .engine import (DecodeMetrics, GenerationServer, StreamingFuture,
                     engines_statusz)
from .kv_cache import PagedKVCache
from .model_fns import CachedDecoder, supports_cached_decode
from .prefix_cache import PrefixCache
from .sampling import sample_next_tokens
from .spec_decode import accept_tokens

__all__ = [
    "GenerationServer", "StreamingFuture", "DecodeMetrics",
    "PagedKVCache", "PrefixCache", "CachedDecoder",
    "supports_cached_decode", "sample_next_tokens", "accept_tokens",
    "engines_statusz",
]
