"""Paged KV cache: device pools + the host-side page allocator.

The device side is a per-layer pool pytree (``model.init_kv_pools``)
shaped ``[num_pages, page_size, heads, head_dim]`` whose contents the
jitted prefill/decode steps update functionally (ops/paged_attention);
the host side here owns which pages belong to whom: a free list, the
per-slot page assignments, and the occupancy/eviction accounting. Page
0 is the reserved trash page (masked writes land there) and is never
handed out.

Thread-safety: the engine's worker thread is the only mutator; the
allocator itself is plain data guarded by the engine lock.
"""
from __future__ import annotations

import math
from typing import List, Optional

__all__ = ["PagedKVCache"]


class PagedKVCache:
    """Host bookkeeping for one pool pytree.

    ``num_pages`` INCLUDES the trash page, so ``capacity`` (allocatable
    pages) is ``num_pages - 1``. ``alloc`` is all-or-nothing: a request
    that cannot get its full reservation gets nothing, so admission
    control can retry later without partial-reservation leaks.
    """

    def __init__(self, model, num_pages: int, page_size: int,
                 dtype=None):
        if num_pages < 2:
            raise ValueError("need at least one allocatable page plus "
                             "the trash page")
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.k, self.v = model.init_kv_pools(self.num_pages,
                                             self.page_size, dtype)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self.evicted_pages_total = 0

    # ---- geometry ----
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` positions."""
        return max(1, math.ceil(tokens / self.page_size))

    # ---- allocation ----
    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """Take ``n_pages`` from the free list, or None (and take
        nothing) if fewer are free."""
        if n_pages > len(self._free):
            return None
        taken = self._free[-n_pages:]
        del self._free[-n_pages:]
        return taken

    def free(self, pages: List[int]):
        """Return a finished sequence's pages (its eviction from the
        cache). The page contents stay as garbage until rewritten —
        correctness relies on block tables, not on zeroing."""
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} out of range")
        self._free.extend(pages)
        self.evicted_pages_total += len(pages)
        if len(self._free) > self.capacity:
            raise RuntimeError("double free: free list exceeds capacity")
