"""Paged KV cache: device pools + the host-side page allocator.

The device side is a per-layer pool pytree (``model.init_kv_pools``)
shaped ``[num_pages, page_size, heads, head_dim]`` whose contents the
jitted prefill/decode steps update functionally (ops/paged_attention);
the host side here owns which pages belong to whom: a free list, the
per-slot page assignments, and the occupancy/eviction accounting. Page
0 is the reserved trash page (masked writes land there) and is never
handed out.

Pages are REFCOUNTED so the prefix cache can share immutable full
pages across sequences (serving/generation/prefix_cache.py): ``alloc``
hands out pages at refcount 1, ``retain`` adds a sharer (a second
sequence mapping the page into its block table, or the prefix index
pinning a published page), and ``release``/``free`` drop one
reference — a page returns to the free list only when its LAST
reference goes away. Freeing a *shared* page therefore decrements
instead of double-returning it (the eviction-accounting bug class
``assert_no_leaks`` exists to catch).

Thread-safety: the engine's worker thread is the only mutator; the
allocator itself is plain data guarded by the engine lock.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

__all__ = ["PagedKVCache"]


class PagedKVCache:
    """Host bookkeeping for one pool pytree.

    ``num_pages`` INCLUDES the trash page, so ``capacity`` (allocatable
    pages) is ``num_pages - 1``. ``alloc`` is all-or-nothing: a request
    that cannot get its full reservation gets nothing, so admission
    control can retry later without partial-reservation leaks.
    """

    def __init__(self, model, num_pages: int, page_size: int,
                 dtype=None, mesh=None):
        if num_pages < 2:
            raise ValueError("need at least one allocatable page plus "
                             "the trash page")
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        # dtype may be the string "int8" — pools then carry per-slot
        # absmax scales alongside int8 values (ops/paged_attention)
        self.kv_dtype = dtype if isinstance(dtype, str) else ""
        self.k, self.v = model.init_kv_pools(self.num_pages,
                                             self.page_size, dtype)
        # serving mesh (serving/mesh.py): heads-sharded committed
        # placement of the pool leaves. EVERYTHING host-side below —
        # free list, refcounts, block tables — is layout-agnostic and
        # identical with or without a mesh; only device bytes move.
        if mesh is not None:
            from ..mesh import ServingMesh
            smesh = mesh if isinstance(mesh, ServingMesh) \
                else ServingMesh(mesh)
            self.k, self.v = smesh.place_pools(self.k, self.v)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}      # page -> live reference count
        self.evicted_pages_total = 0

    # ---- geometry ----
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` positions."""
        return max(1, math.ceil(tokens / self.page_size))

    def pool_bytes(self) -> int:
        """Device bytes resident in the K+V pools (quantized pools
        count their scale planes — that is the honest cost the sizing
        math and shardcheck's projection gate both work from)."""
        import jax
        return sum(int(a.size) * int(a.dtype.itemsize)
                   for a in jax.tree_util.tree_leaves((self.k, self.v)))

    # ---- allocation ----
    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """Take ``n_pages`` from the free list (each at refcount 1), or
        None (and take nothing) if fewer are free."""
        if n_pages > len(self._free):
            return None
        taken = self._free[-n_pages:]
        del self._free[-n_pages:]
        for p in taken:
            self._ref[p] = 1
        return taken

    def retain(self, pages: List[int]) -> None:
        """Add one reference to each already-allocated page — a second
        sequence sharing a cached prefix page, or the prefix index
        pinning a published page."""
        for p in pages:
            if self._ref.get(p, 0) < 1:
                raise ValueError(f"retain of unallocated page {p}")
            self._ref[p] += 1

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def release(self, pages: List[int]) -> int:
        """Drop one reference per page; pages whose last reference goes
        away return to the free list (their eviction from the pool —
        contents stay as garbage until rewritten; correctness relies on
        block tables, not on zeroing). Returns the number of pages
        actually freed, which for shared pages is less than
        ``len(pages)``."""
        freed = 0
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} out of range")
            n = self._ref.get(p, 0)
            if n < 1:
                raise RuntimeError(
                    f"double free: page {p} has no live references")
            if n == 1:
                del self._ref[p]
                self._free.append(p)
                freed += 1
            else:
                self._ref[p] = n - 1
        self.evicted_pages_total += freed
        if len(self._free) > self.capacity:
            raise RuntimeError("double free: free list exceeds capacity")
        return freed

    def free(self, pages: List[int]) -> int:
        """Return a finished sequence's references. Alias of
        ``release`` — kept because "free" is the engine-side verb; a
        SHARED page is only decremented here, never pushed back onto
        the free list while another sequence (or the prefix index)
        still maps it."""
        return self.release(pages)

    # ---- invariants ----
    def leak_check(self) -> dict:
        """Accounting snapshot: free + referenced must cover capacity
        exactly, with no page both free and referenced. Cheap enough
        for /statusz."""
        free_set = set(self._free)
        overlap = sorted(free_set & set(self._ref))
        bad_refs = sorted(p for p, n in self._ref.items() if n < 1)
        return {
            "capacity": self.capacity,
            "free": len(self._free),
            "referenced": len(self._ref),
            "leaked": self.capacity - len(self._free) - len(self._ref),
            "double_booked": overlap,
            "nonpositive_refcounts": bad_refs,
            "ok": (len(self._free) + len(self._ref) == self.capacity
                   and not overlap and not bad_refs),
        }

    def assert_no_leaks(self) -> None:
        """Raise if any page is neither free nor referenced (or both) —
        the refcount-leak tripwire tests and /statusz run after
        admit/share/finish/evict cycles."""
        chk = self.leak_check()
        if not chk["ok"]:
            raise AssertionError(f"KV page accounting leak: {chk}")
