"""Host-side token selection over a batch of next-token logits.

One vectorized numpy pass replaces the per-row ``rng.choice`` loop the
old full-window ``generate()`` ran (O(batch) Python iterations and a
vocab-sized probability normalization per row, per token): softmax and
inverse-CDF selection run across the whole batch at once, and rows can
mix greedy (temperature 0) with sampled selection in the same call.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

__all__ = ["sample_next_tokens"]


def sample_next_tokens(logits: np.ndarray,
                       temperature: Union[float, Sequence[float]],
                       rng: Optional[np.random.RandomState] = None,
                       uniforms: Optional[np.ndarray] = None) -> np.ndarray:
    """Select one token id per row of ``logits`` ``[B, vocab]``.

    ``temperature`` is a scalar or per-row vector; rows at 0 take the
    argmax, rows above 0 sample from ``softmax(logits / t)`` by inverse
    CDF. Randomness comes from ``uniforms`` ``[B]`` in [0, 1) when
    given (the engine draws one uniform per row from each request's own
    RandomState so interleaved batches stay per-request deterministic),
    else from ``rng``. Returns ``[B]`` int64.
    """
    logits = np.asarray(logits, dtype=np.float64)
    b = logits.shape[0]
    temps = np.broadcast_to(np.asarray(temperature, np.float64),
                            (b,)).copy()
    out = logits.argmax(-1).astype(np.int64)
    sampled = temps > 0.0
    if not sampled.any():
        return out
    if uniforms is None:
        if rng is None:
            rng = np.random.RandomState(0)
        uniforms = rng.random_sample(b)
    z = logits[sampled] / temps[sampled, None]
    z -= z.max(-1, keepdims=True)
    p = np.exp(z)
    cdf = np.cumsum(p, -1)
    u = np.asarray(uniforms, np.float64)[sampled] * cdf[:, -1]
    # first index whose cumulative mass exceeds u (strict: u==0 picks
    # the first token with nonzero mass)
    out[sampled] = np.minimum((cdf > u[:, None]).argmax(-1),
                              logits.shape[-1] - 1).astype(np.int64)
    return out
