"""Jitted prefill/decode steps over a cache-capable causal-LM Layer.

``CachedDecoder`` functionalizes the model once (``jit.functional``),
then exposes exactly two device entry points:

- ``prefill(ids, prompt_lens, tables, pools)`` — one forward over a
  padded prompt window that writes the prompt's K/V into the paged
  pools and returns only the last real position's logits ``[B, vocab]``
  (the full ``[B, S, vocab]`` tensor never crosses to the host);
- ``decode(tokens, positions, active, ctx, tables, pools)`` — the
  fixed-shape ``[max_batch, 1]`` decode step: append one position per
  live lane, attend through the block tables, return ``[B, vocab]``;
- ``prefill_chunked(ids, start, seg_lens, tables, pools)`` — suffix
  prefill at a per-row starting position: window tokens attend to the
  already-cached prefix through the block tables (kind="chunked"),
  used after a shared-prefix cache hit so only the unique suffix pays
  prefill; returns the last real position's logits like ``prefill``;
- ``verify(tokens, start, seg_lens, tables, pools)`` — the
  speculative-decoding verify step: ONE fixed-shape
  ``[max_batch, spec_k + 1]`` chunked forward scoring a draft model's
  proposed tokens, returning ALL window logits ``[B, S, vocab]`` so
  the host can run accept-and-resample.

All are ``jax.jit``-compiled with the KV pools donated on backends
that support donation (the pools update in place on device), and both
consult the persistent compile cache (PR 5) first: on a warm
``FLAGS_compile_cache_dir`` the first dispatch of a signature loads a
ready AOT executable instead of tracing + compiling.
"""
from __future__ import annotations

import hashlib
import inspect
import json
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["CachedDecoder", "supports_cached_decode"]


def supports_cached_decode(model) -> bool:
    """True when ``model.forward`` accepts a ``cache`` argument and the
    model can build its own paged pools — the duck-typed contract the
    decode engine and the hybrid-parallel generate helper key on."""
    fwd = getattr(model, "forward", None)
    if fwd is None or not callable(getattr(model, "init_kv_pools", None)):
        return False
    try:
        return "cache" in inspect.signature(fwd).parameters
    except (TypeError, ValueError):  # builtins / C-level callables
        return False


class CachedDecoder:
    """Prefill/decode dispatch for one model instance.

    ``page_size``/``pages_per_seq`` fix the block-table geometry
    (``T = pages_per_seq * page_size`` gathered context slots);
    ``max_batch`` fixes the decode-step shape. The caller owns the pool
    pytree (see ``PagedKVCache``) and threads it through every call —
    when ``donate`` is active the passed-in pools are consumed and MUST
    be replaced by the returned ones.

    Not thread-safe against concurrent mutation of the model's
    parameters (the engine snapshots them here at construction).
    """

    def __init__(self, model, *, max_batch: int, page_size: int,
                 pages_per_seq: int, donate: Optional[bool] = None,
                 max_positions: Optional[int] = None,
                 use_pallas: Optional[bool] = None,
                 kv_dtype: Optional[str] = None, mesh=None):
        import jax

        from ...framework.flags import flag_value
        from ...jit.functional import state_arrays
        from ...models.gpt import GPTKVCache
        from ..mesh import ServingMesh

        if not supports_cached_decode(model):
            raise TypeError(
                f"{type(model).__name__} does not support KV-cached "
                f"decode (forward must accept cache=, and the model "
                f"must expose init_kv_pools)")
        self.model = model
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        # the replica's tensor-parallel mesh (serving/mesh.py): weights
        # shard by the shard.py rule tables, pools along the heads axis.
        # An inert mesh (None or 1 device) leaves EVERYTHING on the
        # single-shard path byte-for-byte — fingerprints, cache keys,
        # placement (regression-tested).
        smesh = mesh if isinstance(mesh, ServingMesh) else ServingMesh(mesh)
        smesh.validate_heads(int(model.kv_cache_spec()["num_heads"]))
        self.serving_mesh = smesh
        # pinned at construction: a flag flip mid-lifetime must not
        # silently retrace half the entry points (both join the
        # geometry fingerprint, so warmup manifests and the persistent
        # compile cache key on them too)
        self.use_pallas = bool(
            flag_value("FLAGS_decode_pallas_attention")
            if use_pallas is None else use_pallas)
        self.kv_dtype = str(
            flag_value("FLAGS_decode_kv_dtype")
            if kv_dtype is None else kv_dtype) or ""
        self.max_positions = int(
            max_positions if max_positions is not None
            else model.kv_cache_spec()["max_seq_len"])
        self._params, self._buffers = state_arrays(model)
        if smesh.live:
            # committed mp-sharded placement: GSPMD partitions every
            # entry point from these operand layouts — no in_shardings
            # needed on the jits
            self._params, self._buffers = smesh.place_state(
                self._params, self._buffers, model=model)
        self._donate = bool(donate) if donate is not None \
            else jax.default_backend() != "cpu"
        self._fp: Optional[str] = None
        # per-signature AOT memo; False marks "tried, unavailable"
        self._aot: Dict[tuple, object] = {}
        self.compiled_signatures = set()    # (site, shape-sig) seen
        # xstats memo: (site, shape-sig) -> ExecEntry
        self._xstats_entries: Dict[tuple, object] = {}

        _Tensor = None

        def _wrap(a):
            nonlocal _Tensor
            if _Tensor is None:
                from ...core.tensor import Tensor
                _Tensor = Tensor
            return _Tensor(a, stop_gradient=True)

        import jax.numpy as jnp

        from ...jit.functional import functional_call

        page = self.page_size
        use_pallas = self.use_pallas
        max_pos = self.max_positions
        # threaded into the traced fns: pool-entry constraints + the
        # per-shard Pallas dispatch (GPTKVCache.mesh); None when inert
        live_mesh = smesh.mesh if smesh.live else None

        from ...distributed.shard import constrain_batch

        def _make_fns(use_pallas):
            # One closure set per kernel path. The real jits below bind
            # the pinned ``self.use_pallas``; the shadow-verification
            # oracle (observability.numerics) rebinds
            # ``use_pallas=False`` to get the pure-JAX reference
            # implementation without touching any dispatch state.

            def _prefill(params, buffers, ids, prompt_lens, tables,
                         k, v):
                # unified-surface batch pin: under a dp serving mesh
                # the prefill window shards by request row; meshless
                # (the single-replica engine default) this is the
                # identity
                ids = constrain_batch(ids)
                # heads-axis pin on the pool operands: GSPMD must never
                # gather a pool (identity when the mesh is inert)
                k = smesh.constrain_pools(k)
                v = smesh.constrain_pools(v)
                b, s = ids.shape
                positions = jnp.broadcast_to(
                    jnp.arange(s, dtype=jnp.int32), (b, s))
                valid = positions < prompt_lens[:, None]
                cache = GPTKVCache(
                    "prefill", page,
                    jax.tree_util.tree_map(_wrap, k),
                    jax.tree_util.tree_map(_wrap, v),
                    _wrap(tables), _wrap(prompt_lens), _wrap(valid),
                    _wrap(positions), use_pallas=use_pallas,
                    mesh=live_mesh)
                logits, (k2, v2) = functional_call(
                    model, params, buffers, ids, cache=cache,
                    training=False)
                # only the last REAL position's logits leave the device
                idx = jnp.clip(prompt_lens.astype(jnp.int32) - 1, 0,
                               s - 1)
                idx = jnp.broadcast_to(idx[:, None, None],
                                       (b, 1, logits.shape[-1]))
                last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
                # tied lm_head leaves logits vocab-sharded under mp:
                # gather ONCE inside the executable, not on the host
                return smesh.replicate(last), k2, v2

            def _decode(params, buffers, tokens, positions, active,
                        ctx, tables, k, v):
                tokens = constrain_batch(tokens)
                k = smesh.constrain_pools(k)
                v = smesh.constrain_pools(v)
                b = tokens.shape[0]
                ids = tokens[:, None]
                cache = GPTKVCache(
                    "decode", page,
                    jax.tree_util.tree_map(_wrap, k),
                    jax.tree_util.tree_map(_wrap, v),
                    _wrap(tables), _wrap(ctx), _wrap(active[:, None]),
                    _wrap(positions[:, None].astype(jnp.int32)),
                    use_pallas=use_pallas, mesh=live_mesh)
                logits, (k2, v2) = functional_call(
                    model, params, buffers, ids, cache=cache,
                    training=False)
                return smesh.replicate(logits[:, 0]), k2, v2

            def _chunked(params, buffers, ids, start, seg_lens, tables,
                         k, v):
                # suffix prefill / speculative verify window: per-row
                # starting positions; attention reaches the cached
                # prefix through the block tables (kind="chunked").
                # Returns ALL window logits [B, S, vocab].
                ids = constrain_batch(ids)
                k = smesh.constrain_pools(k)
                v = smesh.constrain_pools(v)
                b, s = ids.shape
                offs = jnp.arange(s, dtype=jnp.int32)[None, :]
                positions = start.astype(jnp.int32)[:, None] + offs
                # positions past the model's addressable range (a
                # verify window overhanging the budget) write to the
                # trash page and mask themselves out; their logits are
                # garbage the host never consumes
                valid = (offs < seg_lens[:, None]) & (positions < max_pos)
                ctx = (start + seg_lens).astype(jnp.int32)
                cache = GPTKVCache(
                    "chunked", page,
                    jax.tree_util.tree_map(_wrap, k),
                    jax.tree_util.tree_map(_wrap, v),
                    _wrap(tables), _wrap(ctx), _wrap(valid),
                    _wrap(positions), use_pallas=use_pallas,
                    mesh=live_mesh)
                logits, (k2, v2) = functional_call(
                    model, params, buffers, ids, cache=cache,
                    training=False)
                return smesh.replicate(logits), k2, v2

            def _prefill_chunked(params, buffers, ids, start, seg_lens,
                                 tables, k, v):
                logits, k2, v2 = _chunked(params, buffers, ids, start,
                                          seg_lens, tables, k, v)
                b, s = ids.shape
                idx = jnp.clip(seg_lens.astype(jnp.int32) - 1, 0, s - 1)
                idx = jnp.broadcast_to(idx[:, None, None],
                                       (b, 1, logits.shape[-1]))
                last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
                return last, k2, v2

            return {"prefill": _prefill, "decode": _decode,
                    "chunked": _prefill_chunked, "verify": _chunked}

        self._make_fns = _make_fns
        fns = _make_fns(use_pallas)

        donate_pf = (5, 6) if self._donate else ()
        donate_dc = (7, 8) if self._donate else ()
        donate_ck = (6, 7) if self._donate else ()
        self._prefill_jit = jax.jit(fns["prefill"],
                                    donate_argnums=donate_pf)
        self._decode_jit = jax.jit(fns["decode"],
                                   donate_argnums=donate_dc)
        self._chunked_jit = jax.jit(fns["chunked"],
                                    donate_argnums=donate_ck)
        self._verify_jit = jax.jit(fns["verify"],
                                   donate_argnums=donate_ck)
        # shadow-verification support (observability.numerics): oracle
        # jits re-trace the SAME closures with use_pallas=False and NO
        # donation — the oracle runs strictly before the real call so
        # the donated operands are still alive when it reads them.
        # Built lazily: zero cost until the first sampled shadow.
        self._oracle_fns = None
        self._oracle_jits: Dict[str, object] = {}
        self._div_jit = None

    def refresh_params(self):
        """Re-snapshot the model's current parameter arrays (they are
        call operands, not baked constants, so no recompile — the
        hybrid-parallel generate helper calls this per generate() so a
        training step between calls is picked up)."""
        from ...jit.functional import state_arrays
        self._params, self._buffers = state_arrays(self.model)
        if self.serving_mesh.live:
            self._params, self._buffers = self.serving_mesh.place_state(
                self._params, self._buffers, model=self.model)

    # ------------------------------------------------------ identity
    def fingerprint(self) -> str:
        """Stable identity of (model params/config/code, decode
        geometry) for persistent-cache keys and the warmup manifest."""
        if self._fp is None:
            from ...compile_cache import layer_fingerprint
            geom = {"max_batch": self.max_batch,
                    "page_size": self.page_size,
                    "pages_per_seq": self.pages_per_seq,
                    "max_positions": self.max_positions,
                    "donate": self._donate,
                    "use_pallas": self.use_pallas,
                    "kv_dtype": self.kv_dtype, "v": 3}
            # mesh axes + weight spec-tree hash join the geometry ONLY
            # when the mesh is live: an inert (None / 1-device) mesh
            # must reuse today's fingerprints byte-for-byte, and a mesh
            # or spec change must miss every cache keyed on this
            mesh_parts = self.serving_mesh.fingerprint_parts(self.model)
            if mesh_parts is not None:
                geom["serving_mesh"] = mesh_parts
            h = hashlib.sha256(layer_fingerprint(self.model).encode())
            h.update(json.dumps(geom, sort_keys=True).encode())
            self._fp = h.hexdigest()
        return self._fp

    # ------------------------------------------------------ dispatch
    @staticmethod
    def _sig_of(args) -> tuple:
        """Shape signature of the NON-weight operands (params/buffers
        are fixed for this decoder's lifetime — hashing their hundreds
        of leaves per decode step would be pure overhead)."""
        import jax
        return tuple(
            (tuple(int(d) for d in a.shape), str(np.dtype(a.dtype)))
            for a in jax.tree_util.tree_leaves(args[2:]))

    def _aot_exec(self, site: str, jitted, args):
        """Persistent-cache tier (mirrors Predictor._aot_serving_call):
        load-or-compile an AOT executable for this signature, memoized
        per (site, signature, flags generation); any failure degrades
        to the plain jitted dispatch."""
        from ...framework.flags import flag_value, flags_generation
        if not str(flag_value("FLAGS_compile_cache_dir") or ""):
            return None
        sig = (site, flags_generation()) + self._sig_of(args)
        if self.serving_mesh.live:
            # PR 10 pattern: spec-tree edits bump the generation, so a
            # re-annotated model can never hit a stale sharded AOT memo
            from ...distributed.shard import specs_generation
            sig = sig + ("specs_gen", specs_generation())
        memo = self._aot
        if sig in memo:
            fn = memo[sig]
            return fn if fn is not False else None
        fn = None
        try:
            import jax

            from ... import compile_cache as cc
            cache = cc.default_cache()
            if cache is not None:
                specs = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        tuple(a.shape), np.dtype(a.dtype)), args)
                key, parts = cc.cache_key(
                    self.fingerprint(), list(specs),
                    mesh=self.serving_mesh.mesh_for_cache_key(),
                    extra={"site": site})
                fn, _hit = cache.get_or_compile(
                    key, lambda: jitted.lower(*specs).compile(),
                    site=site, meta=parts,
                    xstats_meta=self._xstats_meta(site, jitted, args))
        except Exception:  # noqa: BLE001 - AOT is an optimization
            fn = None      # tier; never let it break decode
        memo[sig] = fn if fn is not None else False
        return fn

    def _xstats_meta(self, site: str, jitted, args):
        """xstats registration payload for one decode entry point:
        decoder identity + a lower thunk over abstract operand specs
        (scrape-time only; params/buffers abstracted too)."""
        try:
            import jax

            from ...observability import xstats
            if not xstats.enabled():
                return None
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    tuple(a.shape), np.dtype(a.dtype)), args)
            return {"signature": self._sig_of(args),
                    "fingerprint": self.fingerprint(),
                    "lower_thunk": lambda: jitted.lower(*specs)}
        except Exception:  # noqa: BLE001 - observability is garnish
            return None

    def _xstats_note(self, site: str, sig: tuple, jitted, args,
                     used_aot: bool):
        """Per-dispatch note into the xstats registry (memoized by
        (site, signature) — steady-state cost is one dict hit plus a
        counter, on a path that just paid a device step)."""
        try:
            from ...observability import xstats
            if not xstats.enabled():
                return
            ent = self._xstats_entries.get(sig)
            if ent is None:
                xsig = sig[1:]   # drop the site prefix: site is the key
                if used_aot:
                    ent = xstats.register_executable(site, xsig)
                else:
                    meta = self._xstats_meta(site, jitted, args) or {}
                    ent = xstats.register_executable(
                        site, xsig,
                        fingerprint=meta.get("fingerprint"),
                        provenance={"cache": "off"},
                        lower_thunk=meta.get("lower_thunk"))
                if ent is None:
                    return
                self._xstats_entries[sig] = ent
            xstats.note_dispatch(ent)
        except Exception:  # noqa: BLE001 - never break a decode step
            pass

    # ------------------------------------------- numerics tripwires
    _ORACLE_KEYS = {"generate_decode": "decode",
                    "generate_chunked": "chunked",
                    "generate_verify": "verify"}

    def _oracle_jit(self, site: str):
        """Non-donating pure-JAX jit for a shadow-verified site, built
        from the same closure factory as the real entry points but
        with ``use_pallas=False`` (the reference implementation)."""
        fn = self._oracle_jits.get(site)
        if fn is None:
            import jax
            if self._oracle_fns is None:
                self._oracle_fns = self._make_fns(False)
            fn = jax.jit(self._oracle_fns[self._ORACLE_KEYS[site]])
            self._oracle_jits[site] = fn
        return fn

    def _divergence_fn(self):
        if self._div_jit is None:
            import jax
            import jax.numpy as jnp
            self._div_jit = jax.jit(
                lambda a, b: jnp.max(jnp.abs(
                    a.astype(jnp.float32) - b.astype(jnp.float32))))
        return self._div_jit

    def _numerics_shadow(self, site: str, args):
        """Sampled shadow re-execution through the pure-JAX oracle.
        MUST run before the real (possibly donating) call: the oracle
        jit never donates, and enqueue order guarantees it reads the
        pools before the real executable consumes them."""
        try:
            from ...observability import numerics
            if site not in numerics.SHADOW_SITES:
                return None
            if not numerics.sample_decision(numerics.shadow_rate()):
                return None
            return self._oracle_jit(site)(*args)
        except Exception:  # noqa: BLE001 - observability is garnish
            return None

    def _numerics_note(self, site: str, out, shadow_out):
        try:
            from ...observability import numerics
            kind = site[len("generate_"):]
            if shadow_out is not None:
                div = self._divergence_fn()(out[0], shadow_out[0])
                numerics.note_shadow_divergence(
                    kind, self.kv_dtype or "f32", div)
            if numerics.sample_decision(numerics.tripwire_rate()):
                numerics.note_serving_logits(kind, out[0])
                if self.kv_dtype == "int8":
                    numerics.note_int8_scales(kind, out[1], out[2])
        except Exception:  # noqa: BLE001 - never break a decode step
            pass

    def _dispatch(self, site: str, jitted, args) -> Tuple[object, bool]:
        """Returns ``(outputs, was_new_signature)``."""
        sig = (site,) + self._sig_of(args)
        fresh = sig not in self.compiled_signatures
        self.compiled_signatures.add(sig)
        shadow_out = self._numerics_shadow(site, args)
        aot = self._aot_exec(site, jitted, args)
        fn = aot or jitted
        out = fn(*args)
        self._xstats_note(site, sig, jitted, args, aot is not None)
        self._numerics_note(site, out, shadow_out)
        return out, fresh

    def prefill(self, ids: np.ndarray, prompt_lens: np.ndarray,
                tables: np.ndarray, k, v):
        """ids [B, S] int64 (left-aligned, zero-padded); prompt_lens
        [B] int32 (0 = dead pad row); tables [B, P] int32. Returns
        ``(last_logits [B, vocab] jax array, k', v', new_signature)``."""
        args = (self._params, self._buffers,
                np.ascontiguousarray(ids, np.int64),
                np.ascontiguousarray(prompt_lens, np.int32),
                np.ascontiguousarray(tables, np.int32), k, v)
        (last, k2, v2), fresh = self._dispatch(
            "generate_prefill", self._prefill_jit, args)
        return last, k2, v2, fresh

    def prefill_chunked(self, ids: np.ndarray, start: np.ndarray,
                        seg_lens: np.ndarray, tables: np.ndarray, k, v):
        """Suffix prefill after a prefix-cache hit. ids [B, S] int64
        (left-aligned suffix tokens); start [B] int32 per-row absolute
        offset (= matched prefix length, 0 = dead row); seg_lens [B]
        int32 real suffix lengths; tables [B, P] int32 (prefix pages
        first, then the row's private pages). Returns ``(last_logits
        [B, vocab] jax array, k', v', new_signature)``."""
        args = (self._params, self._buffers,
                np.ascontiguousarray(ids, np.int64),
                np.ascontiguousarray(start, np.int32),
                np.ascontiguousarray(seg_lens, np.int32),
                np.ascontiguousarray(tables, np.int32), k, v)
        (last, k2, v2), fresh = self._dispatch(
            "generate_chunked", self._chunked_jit, args)
        return last, k2, v2, fresh

    def verify(self, tokens: np.ndarray, start: np.ndarray,
               seg_lens: np.ndarray, tables: np.ndarray, k, v):
        """Speculative verify: one fixed-shape chunked forward over the
        [last_accepted, d_1..d_k] window per lane, returning ALL window
        logits ``[B, S, vocab]`` (S = spec_k + 1) so the host judges
        every proposal in one device step. Rejected positions' K/V
        writes land on the lane's already-reserved pages and are rolled
        back by context-length truncation, never by pool mutation."""
        args = (self._params, self._buffers,
                np.ascontiguousarray(tokens, np.int64),
                np.ascontiguousarray(start, np.int32),
                np.ascontiguousarray(seg_lens, np.int32),
                np.ascontiguousarray(tables, np.int32), k, v)
        (logits, k2, v2), fresh = self._dispatch(
            "generate_verify", self._verify_jit, args)
        return logits, k2, v2, fresh

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               active: np.ndarray, ctx: np.ndarray,
               tables: np.ndarray, k, v):
        """One fixed-shape decode step. tokens [B] int64; positions [B]
        int32 (slot being written); active [B] bool; ctx [B] int32
        visible length INCLUDING this token; tables [B, P] int32.
        Returns ``(logits [B, vocab] jax array, k', v',
        new_signature)``."""
        args = (self._params, self._buffers,
                np.ascontiguousarray(tokens, np.int64),
                np.ascontiguousarray(positions, np.int32),
                np.ascontiguousarray(active, bool),
                np.ascontiguousarray(ctx, np.int32),
                np.ascontiguousarray(tables, np.int32), k, v)
        (logits, k2, v2), fresh = self._dispatch(
            "generate_decode", self._decode_jit, args)
        return logits, k2, v2, fresh
