"""Radix index over immutable, full KV pages, keyed by token content.

RadixAttention-style prefix sharing (SGLang) adapted to the paged-pool
substrate: a trie whose every edge is ONE FULL PAGE of token ids
(``page_size`` tokens), mapping a token-content prefix to the pool
pages that already hold its K/V. A request whose prompt walks the trie
reuses those pages directly in its block table — the shared 500-token
preamble prefills once per process; later requests pay prefill only
for their unique suffix.

Sharing rules (the copy-on-write contract):

- Only FULL pages are ever shared, so a shared page is immutable by
  construction: a sequence writes K/V only at positions at or beyond
  its matched prefix, and a page-aligned match puts every write into
  the sequence's own private pages. The divergence page — the first
  page where a request's tokens differ, or its final partial page —
  is always materialized privately (allocated fresh and recomputed by
  the suffix prefill): copy-on-write implemented as
  recompute-into-private-copy, which costs at most ``page_size - 1``
  redundant token prefills and never a device-side page copy, so no
  new executable shapes appear.
- A published page carries one index reference (``PagedKVCache.retain``)
  on top of any sequence references. Pages whose ONLY reference is the
  index are *cached* (reusable but reclaimable); under pool pressure
  ``evict`` releases them leaf-first in LRU order — interior nodes are
  never dropped before their descendants, since a lookup must walk an
  unbroken chain from the root.
- Nodes are published only AFTER the prefill/decode step that wrote
  the page content completed, so a matched page always holds valid
  K/V (the engine publishes under its lock, from the worker thread).

Thread-safety: like the allocator, plain data mutated only under the
engine lock.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .kv_cache import PagedKVCache

__all__ = ["PrefixCache"]


class _Node:
    """One full page of the trie: ``tokens`` (length page_size) is the
    edge label, ``page`` the pool page holding that span's K/V."""

    __slots__ = ("tokens", "page", "children", "parent", "last_used")

    def __init__(self, tokens: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.tokens = tokens
        self.page = page
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """The radix index over one ``PagedKVCache`` pool."""

    def __init__(self, kv: PagedKVCache):
        self.kv = kv
        self.page_size = kv.page_size
        # root is a sentinel: children keyed by the first page's tokens
        self._root = _Node((), 0, None)
        self._clock = 0            # monotone LRU tick
        self._n_nodes = 0
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.pages_published = 0
        self.pages_evicted = 0

    def __len__(self) -> int:
        return self._n_nodes

    @property
    def cached_pages(self) -> int:
        return self._n_nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------------------------------- lookup
    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest page-aligned cached prefix of ``tokens``.

        Returns ``(matched_tokens, pages)`` where ``matched_tokens`` is
        a multiple of ``page_size`` and STRICTLY less than
        ``len(tokens)`` — at least one token is always left for the
        suffix prefill to produce first-token logits. Touches matched
        nodes' LRU clocks; does NOT retain (the caller retains under
        the engine lock while it maps the pages into a block table)
        and does NOT bump hit/miss stats (admission can retry the same
        head-of-line request many times; the engine counts once per
        actual admission via ``note_admission``).
        """
        ps = self.page_size
        tokens = [int(t) for t in tokens]
        node = self._root
        pages: List[int] = []
        tick = self._tick()
        i = 0
        while i + ps < len(tokens):       # strict: keep >= 1 suffix token
            key = tuple(tokens[i:i + ps])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = tick
            pages.append(child.page)
            node = child
            i += ps
        return i, pages

    def note_admission(self, matched_tokens: int) -> None:
        """Count one admitted request's outcome (hit when any prefix
        tokens were reused)."""
        if matched_tokens > 0:
            self.hits += 1
            self.tokens_reused += int(matched_tokens)
        else:
            self.misses += 1

    # ------------------------------------------------------ publish
    def publish(self, tokens: Sequence[int], pages: Sequence[int],
                n_tokens: Optional[int] = None) -> int:
        """Insert the chain of FULL pages covering ``tokens[:n_tokens]``
        whose K/V now lives in ``pages`` (the sequence's block-table
        order). Existing nodes are kept (first writer wins; a duplicate
        page stays private and frees with its sequence); each newly
        published page gains one index reference. Returns the number of
        pages newly published."""
        ps = self.page_size
        n = len(tokens) if n_tokens is None else int(n_tokens)
        n_full = n // ps
        node = self._root
        tick = self._tick()
        fresh = 0
        for pi in range(n_full):
            key = tuple(int(t) for t in tokens[pi * ps:(pi + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = int(pages[pi])
                if self.kv.refcount(page) < 1:
                    break      # defensive: never index an unowned page
                self.kv.retain([page])
                child = _Node(key, page, node)
                node.children[key] = child
                self._n_nodes += 1
                fresh += 1
            child.last_used = tick
            node = child
        self.pages_published += fresh
        return fresh

    # ------------------------------------------------------ eviction
    def _evictable_leaves(self) -> List[_Node]:
        """Leaves whose page the index holds the ONLY reference to —
        shared-with-a-live-sequence pages are pinned (refcount > 1)."""
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.kv.refcount(n.page) == 1:
                out.append(n)
        return out

    def evict(self, n_pages: int) -> int:
        """Release up to ``n_pages`` cached pages, LRU leaf-first
        (evicting a leaf can expose its parent as the next leaf).
        Returns the number of pages actually freed back to the pool."""
        freed = 0
        while freed < n_pages:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves:
                if freed >= n_pages:
                    break
                freed += self._drop(leaf)
        self.pages_evicted += freed
        return freed

    def clear(self) -> int:
        """Empty the whole index, releasing its reference on EVERY
        node — including pages still pinned by live sequences, which
        stay allocated to those sequences but can no longer be
        matched. The weight-swap invalidation path: cached K/V
        computed under old weights must never serve a new-weight
        request. Returns the number of pages freed to the pool."""
        nodes: List[_Node] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            nodes.append(n)
            stack.extend(n.children.values())
        freed = 0
        for n in nodes:
            freed += self.kv.release([n.page])
        self._root.children = {}
        self._n_nodes = 0
        self.pages_evicted += freed
        return freed

    def _drop(self, node: _Node) -> int:
        assert not node.children
        parent = node.parent
        del parent.children[node.tokens]
        self._n_nodes -= 1
        return self.kv.release([node.page])

    # ------------------------------------------------------ reporting
    def stats(self) -> dict:
        return {
            "cached_pages": self._n_nodes,
            "hits": self.hits,
            "misses": self.misses,
            "tokens_reused": self.tokens_reused,
            "pages_published": self.pages_published,
            "pages_evicted": self.pages_evicted,
        }
