"""GenerationServer: continuous-batching autoregressive decode serving.

Orca-style iteration-level scheduling (Yu et al., OSDI '22) over the
paged KV cache: the in-flight decode batch is re-formed EVERY step —
new sequences join as soon as a slot and pages free up, finished ones
are evicted the step they finish — instead of the reap-and-dispatch
barrier the batch-predict server uses. There is exactly one compiled
decode shape, ``[max_batch, 1]`` with dead lanes slot-masked, so the
whole decode lattice is two signatures (prefill buckets + the decode
step) and a warm PR 5 compile cache makes it cold-start free.

Flow per worker iteration:

1. **admit**: pop FIFO requests while a batch slot AND their full page
   reservation are available; drop expired ones
   (``DeadlineExceededError``, matching ``submit`` semantics — a
   deadline gates scheduling, never an in-flight stream). Admission
   consults the shared-prefix radix index (prefix_cache.py) first:
   matched full pages are mapped into the block table (refcounted
   sharing), shrinking the reservation AND the prefill window.
2. **prefill**: admitted prompts run one forward at their (pow2-row,
   seq-bucket) shape — the PR 1/2 bucket lattice — writing prompt K/V
   into their pages and sampling the first token. Prefix hits run the
   CHUNKED suffix prefill instead (attention reaches the cached
   prefix through the block tables), then every prompt's full pages
   are published to the index.
3. **decode**: one fixed-shape step for every live lane; sample on
   host (vectorized, per-request RNG), stream tokens out through each
   request's ``StreamingFuture``. With a draft model configured, each
   iteration is instead draft-propose-k + ONE fixed-shape
   ``[max_batch, k+1]`` verify step with accept-and-resample
   (speculative decoding; output distribution unchanged).
4. **evict**: eos / length / cancelled sequences release pages
   immediately (KV page eviction), freeing admission capacity for the
   next iteration; completed sequences' full pages stay behind in the
   prefix index (refcount 1) until pool pressure LRU-evicts them.

Backpressure mirrors ``InferenceServer.submit``: a bounded queue
raising ``QueueFullError``, ``ServerClosedError`` after shutdown, and
a fault barrier that fails only the affected requests, never the
worker.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...observability import tracing
from ..bucketing import ShapeBucketPolicy
from ..request import (DeadlineExceededError, QueueFullError,
                       QuotaExceededError, ServerClosedError)
from .kv_cache import PagedKVCache
from .model_fns import CachedDecoder, supports_cached_decode
from .prefix_cache import PrefixCache
from .sampling import sample_next_tokens
from .spec_decode import accept_tokens, softmax

__all__ = ["GenerationServer", "StreamingFuture", "DecodeMetrics",
           "engines_statusz"]

# live-engine registry for /statusz (weak: a dropped engine vanishes)
_ENGINES_LOCK = threading.Lock()
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


def engines_statusz() -> dict:
    """``/statusz`` section: every live engine's prefix-cache,
    speculative and page-accounting state (incl. the refcount-leak
    check)."""
    with _ENGINES_LOCK:
        engines = list(_ENGINES)
    return {e.metrics.name: e.statusz() for e in engines}


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class StreamingFuture:
    """A generation request's result handle: tokens land one by one as
    the engine emits them.

    Consumer surface: iterate (``for tok in fut``) to stream, or
    ``result(timeout)`` to block for the complete generated-token list;
    ``tokens()`` snapshots what has landed so far; ``cancel()`` asks
    the engine to evict the sequence at its next step. A failed
    request raises its exception from ``result()``/iteration.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._toks: List[int] = []
        self._exc: Optional[BaseException] = None
        self._done = False
        self._finish_reason: Optional[str] = None
        self._cancel_requested = False
        self._on_cancel = None

    # ---- consumer ----
    def __iter__(self):
        i = 0
        while True:
            with self._cond:
                while len(self._toks) <= i and not self._done:
                    self._cond.wait()
                if i < len(self._toks):
                    tok = self._toks[i]
                    i += 1
                else:
                    if self._exc is not None:
                        raise self._exc
                    return
            yield tok       # outside the lock: consumer code may block

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; returns ALL generated token
        ids (eos included when one was produced)."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout):
                raise TimeoutError("generation still streaming")
            if self._exc is not None:
                raise self._exc
            return list(self._toks)

    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._toks)

    def done(self) -> bool:
        with self._cond:
            return self._done

    def exception(self) -> Optional[BaseException]:
        with self._cond:
            return self._exc

    @property
    def finish_reason(self) -> Optional[str]:
        """"eos" | "length" | "cancelled" | "error" | deadline/shutdown
        reasons; None while streaming."""
        with self._cond:
            return self._finish_reason

    def cancel(self) -> bool:
        """Request eviction; returns False when already finished. The
        engine honors it at its next harvest — tokens already emitted
        stay available. A registered cancel hook (the fleet router's
        socket-close propagation) fires outside the lock, so a routed
        stream's cancellation reaches the replica instead of only
        stopping client-side iteration."""
        with self._cond:
            if self._done:
                return False
            self._cancel_requested = True
            hook = self._on_cancel
        if hook is not None:
            try:
                hook()
            except Exception:  # noqa: BLE001 - propagation is best-
                pass           # effort; local cancel already holds
        return True

    def _set_cancel_hook(self, hook):
        """Install/clear the propagation hook; when cancellation was
        already requested, fire immediately (the cancel raced the
        hook installation)."""
        with self._cond:
            self._on_cancel = hook
            fire = hook is not None and self._cancel_requested \
                and not self._done
        if fire:
            try:
                hook()
            except Exception:  # noqa: BLE001 - as above
                pass

    def cancelled(self) -> bool:
        with self._cond:
            return self._finish_reason == "cancelled"

    # ---- engine side ----
    def _emit(self, tok: int):
        with self._cond:
            self._toks.append(int(tok))
            self._cond.notify_all()

    def _finish(self, reason: str):
        with self._cond:
            if self._done:
                return
            self._done = True
            self._finish_reason = reason
            self._cond.notify_all()

    def _fail(self, exc: BaseException, reason: str = "error"):
        with self._cond:
            if self._done:
                return
            self._exc = exc
            self._done = True
            self._finish_reason = reason
            self._cond.notify_all()


class _Request:
    __slots__ = ("prompt", "max_new", "temperature", "rng", "future",
                 "submit_t", "deadline", "hard_deadline", "trace",
                 "t_wall_ns", "tenant", "prio_rank", "n_done", "cost")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 temperature: float, seed: Optional[int],
                 timeout_ms: Optional[float], trace=None,
                 deadline_ms: Optional[float] = None,
                 tenant: str = "default", prio_rank: int = 1,
                 n_done: int = 0):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.rng = np.random.RandomState(seed)
        self.future = StreamingFuture()
        self.submit_t = time.monotonic()
        self.deadline = (self.submit_t + timeout_ms / 1e3
                         if timeout_ms else None)
        # the HARD end-to-end budget (fleet deadline propagation): an
        # in-flight stream past it is evicted at batch re-form, unlike
        # the scheduling-only ``deadline`` above
        self.hard_deadline = (self.submit_t + deadline_ms / 1e3
                              if deadline_ms else None)
        # trace identity (tracing.TraceContext child whose span id is
        # the generate::request root span); warmup never builds a
        # _Request, so warmup traffic is structurally untraced
        self.trace = trace
        self.t_wall_ns = time.time_ns() if trace is not None else 0
        # multi-tenant scheduling: the WFQ cost is token-denominated
        # (prompt + generation budget); n_done counts tokens already
        # streamed before a park/resume cycle, so TTFT and max_new
        # accounting survive preemption
        self.tenant = tenant
        self.prio_rank = int(prio_rank)
        self.n_done = int(n_done)
        self.cost = float(len(prompt) + self.max_new)

    def expired(self, now: float) -> bool:
        if self.deadline is not None and now > self.deadline:
            return True
        return self.hard_expired(now)

    def hard_expired(self, now: float) -> bool:
        return self.hard_deadline is not None and \
            now > self.hard_deadline


class _ActiveSeq:
    """One live lane of the in-flight decode batch."""

    __slots__ = ("req", "slot", "pages", "ctx", "max_total",
                 "last_token", "n_generated", "last_emit_t",
                 "prefix_len", "history", "draft_ctx", "published")

    def __init__(self, req: _Request, slot: int, pages: List[int],
                 max_total: int, prefix_len: int = 0):
        self.req = req
        self.slot = slot
        self.pages = pages              # prefix pages first, private after
        self.ctx = len(req.prompt)      # tokens whose K/V is cached
        self.max_total = max_total      # prompt + generation budget
        self.last_token = -1
        self.n_generated = 0
        self.last_emit_t = 0.0
        self.prefix_len = int(prefix_len)   # cached tokens reused
        # full token history (prompt + emitted) — spec-decode draft
        # catch-up and publish-on-completion both key pages by content
        self.history: List[int] = [int(t) for t in req.prompt]
        self.draft_ctx = len(req.prompt)    # draft-pool cached tokens
        self.published = False              # prompt pages in the index


_EVENTS = ("submitted", "completed", "rejected", "timed_out",
           "cancelled", "failed", "parked", "preempted", "resumed")


class DecodeMetrics:
    """Decode-serving metric families on the PR 3 registry, plus
    bounded windows for the JSON snapshot percentiles."""

    def __init__(self, name: str, max_batch: int, page_capacity: int,
                 window: int = 2048, registry=None):
        from ...observability.registry import (PercentileWindow,
                                               default_registry)
        self.name = name
        self._lock = threading.Lock()
        reg = registry or default_registry()
        occ_buckets = sorted({1, 2, 4, 8, 16, 32, 64, 128,
                              max(1, int(max_batch))})
        self._f_events = reg.counter(
            "paddle_decode_requests_total",
            "generation request lifecycle events per engine",
            ("server", "event"))
        self._f_tokens = reg.counter(
            "paddle_decode_tokens_total",
            "tokens emitted by the decode engine", ("server",))
        self._f_inter = reg.histogram(
            "paddle_decode_inter_token_ms",
            "latency between consecutive streamed tokens of a sequence",
            ("server",))
        self._f_step = reg.histogram(
            "paddle_decode_step_ms",
            "device step durations by stage (prefill batch / decode "
            "iteration)", ("server", "stage"))
        self._f_occ = reg.histogram(
            "paddle_decode_batch_occupancy",
            "live lanes per decode iteration (continuous-batching "
            "utilization of the fixed [max_batch, 1] step)",
            ("server",), buckets=occ_buckets)
        self._f_pages = reg.gauge(
            "paddle_decode_kv_pages",
            "KV-cache page occupancy by state", ("server", "state"))
        self._f_pool_bytes = reg.gauge(
            "paddle_decode_kv_pool_bytes",
            "resident device bytes of the paged K/V pools (quantized "
            "pools include their scale planes)", ("server", "dtype"))
        self._f_evict = reg.counter(
            "paddle_decode_kv_page_evictions_total",
            "pages released by finished/cancelled sequences",
            ("server",))
        self._f_compile = reg.counter(
            "paddle_decode_compile_total",
            "decode-engine dispatch signatures by compile-cache result",
            ("server", "result"))
        self._f_ttft = reg.histogram(
            "paddle_decode_ttft_ms",
            "time to first token: submit to first streamed token "
            "(prefix-cache hits collapse the prefill share)",
            ("server",))
        self._f_pfx_hits = reg.counter(
            "paddle_decode_prefix_hits_total",
            "admissions whose prompt reused cached prefix pages",
            ("server",))
        self._f_pfx_reused = reg.counter(
            "paddle_decode_prefix_tokens_reused_total",
            "prompt tokens served from the prefix cache instead of "
            "prefill", ("server",))
        self._f_spec_prop = reg.counter(
            "paddle_decode_spec_proposed_tokens_total",
            "draft-model tokens proposed to the verify step",
            ("server",))
        self._f_spec_acc = reg.counter(
            "paddle_decode_spec_accepted_tokens_total",
            "proposed tokens the target model accepted", ("server",))
        for fam in (self._f_events, self._f_tokens, self._f_inter,
                    self._f_step, self._f_occ, self._f_pages,
                    self._f_pool_bytes,
                    self._f_evict, self._f_compile, self._f_ttft,
                    self._f_pfx_hits, self._f_pfx_reused,
                    self._f_spec_prop, self._f_spec_acc):
            fam.clear(server=name)
        self._events = {e: self._f_events.labels(server=name, event=e)
                        for e in _EVENTS}
        self._c_tokens = self._f_tokens.labels(server=name)
        self._h_inter = self._f_inter.labels(server=name)
        self._h_step = {s: self._f_step.labels(server=name, stage=s)
                        for s in ("prefill", "decode")}
        self._h_occ = self._f_occ.labels(server=name)
        self._g_used = self._f_pages.labels(server=name, state="used")
        self._g_free = self._f_pages.labels(server=name, state="free")
        self._c_evict = self._f_evict.labels(server=name)
        self._c_hit = self._f_compile.labels(server=name, result="hit")
        self._c_miss = self._f_compile.labels(server=name,
                                              result="miss")
        self._h_ttft = self._f_ttft.labels(server=name)
        self._c_pfx_hits = self._f_pfx_hits.labels(server=name)
        self._c_pfx_reused = self._f_pfx_reused.labels(server=name)
        self._c_spec_prop = self._f_spec_prop.labels(server=name)
        self._c_spec_acc = self._f_spec_acc.labels(server=name)
        self._w_inter = PercentileWindow(int(window))
        self._w_ttft = PercentileWindow(int(window))
        self._w_step = {s: PercentileWindow(int(window))
                        for s in ("prefill", "decode")}
        self._occ_sum = 0
        self._occ_n = 0
        self._page_capacity = int(page_capacity)
        self._pool_bytes = 0
        self._pool_dtype = "model"

    def count(self, event: str, n: int = 1):
        self._events[event].inc(n)

    def observe_tokens(self, n: int):
        self._c_tokens.inc(n)

    def observe_inter_token(self, ms_list: Sequence[float]):
        ms_list = [float(m) for m in ms_list]
        if not ms_list:
            return
        with self._lock:
            self._w_inter.extend(ms_list)
        self._h_inter.observe_many(ms_list)

    def observe_step(self, stage: str, ms: float):
        with self._lock:
            self._w_step[stage].observe(float(ms))
        self._h_step[stage].observe(float(ms))

    def observe_occupancy(self, n_active: int):
        with self._lock:
            self._occ_sum += int(n_active)
            self._occ_n += 1
        self._h_occ.observe(n_active)

    def set_kv_pages(self, used: int, free: int):
        self._g_used.set(used)
        self._g_free.set(free)

    def set_kv_pool_bytes(self, nbytes: int, dtype: str):
        self._pool_bytes = int(nbytes)
        self._pool_dtype = dtype or "model"
        self._f_pool_bytes.labels(
            server=self.name, dtype=self._pool_dtype).set(int(nbytes))

    def observe_evictions(self, n_pages: int):
        self._c_evict.inc(n_pages)

    def observe_compile(self, hit: bool):
        (self._c_hit if hit else self._c_miss).inc()

    def observe_ttft(self, ms: float):
        with self._lock:
            self._w_ttft.observe(float(ms))
        self._h_ttft.observe(float(ms))

    def observe_prefix_hit(self, tokens_reused: int):
        self._c_pfx_hits.inc()
        self._c_pfx_reused.inc(int(tokens_reused))

    def observe_spec(self, proposed: int, accepted: int):
        self._c_spec_prop.inc(int(proposed))
        self._c_spec_acc.inc(int(accepted))

    def snapshot(self) -> dict:
        with self._lock:
            occ = (self._occ_sum / self._occ_n) if self._occ_n else 0.0
            return {
                "server": self.name,
                "counters": {e: int(c.value)
                             for e, c in self._events.items()},
                "tokens_total": int(self._c_tokens.value),
                "ttft_ms": self._w_ttft.snapshot(),
                "inter_token_ms": self._w_inter.snapshot(),
                "step_ms": {s: w.snapshot()
                            for s, w in self._w_step.items()},
                "batch_occupancy": {"mean": occ, "steps": self._occ_n},
                "kv_pages": {"capacity": self._page_capacity,
                             "used": int(self._g_used.value),
                             "free": int(self._g_free.value),
                             "evicted_total": int(self._c_evict.value),
                             "pool_bytes": self._pool_bytes,
                             "pool_dtype": self._pool_dtype},
                "compile_cache": {"hits": int(self._c_hit.value),
                                  "misses": int(self._c_miss.value)},
                "prefix": {
                    "hits": int(self._c_pfx_hits.value),
                    "tokens_reused": int(self._c_pfx_reused.value)},
                "spec": {
                    "proposed": int(self._c_spec_prop.value),
                    "accepted": int(self._c_spec_acc.value),
                    "acceptance_rate": (
                        int(self._c_spec_acc.value)
                        / max(1, int(self._c_spec_prop.value)))},
            }


class GenerationServer:
    """Continuous-batching decode engine over one cache-capable
    causal-LM Layer (``GPTForCausalLM`` or anything matching
    ``model_fns.supports_cached_decode``).

    ``submit_generate(prompt, ...) -> StreamingFuture`` with bounded-
    queue backpressure and scheduling deadlines; parameters default to
    the ``FLAGS_decode_*`` knobs. The model is snapshot at construction
    (weight updates after construction are not picked up) and put in
    eval mode.
    """

    def __init__(self, model, *, max_batch: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 queue_capacity: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: int = 0,
                 donate: Optional[bool] = None,
                 name: str = "generate",
                 telemetry_port: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 draft_model=None,
                 spec_k: Optional[int] = None,
                 scheduler=None,
                 mesh=None,
                 start: bool = True):
        model.eval()
        self.model = model
        spec = model.kv_cache_spec()
        # tensor-parallel replica mesh (serving/mesh.py): None defers
        # to FLAGS_serving_mesh_mp, read ONCE here like the other
        # decode knobs. The mesh is threaded EXPLICITLY through the
        # decoder and pools — the engine's worker thread never sees a
        # caller's thread-local global mesh.
        from ..mesh import ServingMesh, serving_mesh_from_flags
        if mesh is None:
            self.serving_mesh = serving_mesh_from_flags()
        else:
            self.serving_mesh = mesh if isinstance(mesh, ServingMesh) \
                else ServingMesh(mesh)
        self.serving_mesh.validate_heads(int(spec["num_heads"]))
        self.max_batch = int(max_batch if max_batch is not None
                             else _flag("FLAGS_decode_max_batch", 8))
        self.page_size = int(page_size if page_size is not None
                             else _flag("FLAGS_decode_page_size", 16))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else spec["max_seq_len"])
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.pages_per_seq = -(-self.max_seq_len // self.page_size)
        # fused-kernel / quantized-pool knobs: read ONCE here and
        # pinned for the engine's lifetime (they join the decoder's
        # geometry fingerprint, so warmup manifests and the persistent
        # compile cache never mix executables across a flag flip)
        from ...ops.paged_attention import kv_pool_bytes, resolve_kv_dtype
        self.use_pallas = bool(_flag("FLAGS_decode_pallas_attention",
                                     False))
        self.kv_dtype = str(_flag("FLAGS_decode_kv_dtype", "") or "")
        resolve_kv_dtype(self.kv_dtype)   # fail fast on a typo'd dtype
        nh, hd = spec["num_heads"], spec["head_dim"]
        f32_tok = kv_pool_bytes(1, 1, nh, hd, None)
        cur_tok = kv_pool_bytes(1, 1, nh, hd, self.kv_dtype or None)
        # sub-f32 pools grant extra resident sequences for the SAME
        # device budget: int8 (~3.8x smaller) and bf16 (2x) both size
        # to 2x pages ≈ 2x concurrently-resident sequences
        self.kv_capacity_factor = max(1, min(2, f32_tok // max(cur_tok,
                                                               1)))
        if num_pages is None:
            num_pages = int(_flag("FLAGS_decode_kv_pages", 0))
        if not num_pages:
            num_pages = 1 + (self.max_batch * self.pages_per_seq
                             * self.kv_capacity_factor)
        self.default_timeout_ms = default_timeout_ms \
            if default_timeout_ms is not None \
            else (_flag("FLAGS_decode_default_timeout_ms", 0.0) or None)
        cap = queue_capacity if queue_capacity is not None \
            else _flag("FLAGS_decode_queue_capacity", 64)
        self.queue_capacity = int(cap)
        if seq_buckets is None:
            seq_buckets, b = [], 8
            while b < self.max_seq_len:
                seq_buckets.append(b)
                b <<= 1
            seq_buckets.append(self.max_seq_len)
        self.policy = ShapeBucketPolicy(
            max_batch_size=self.max_batch, pad_batch=True,
            seq_buckets=seq_buckets, seq_axis=1)
        self.decoder = CachedDecoder(
            model, max_batch=self.max_batch, page_size=self.page_size,
            pages_per_seq=self.pages_per_seq, donate=donate,
            max_positions=self.max_seq_len,
            use_pallas=self.use_pallas, kv_dtype=self.kv_dtype,
            mesh=self.serving_mesh)
        self.kv = PagedKVCache(model, num_pages=int(num_pages),
                               page_size=self.page_size,
                               dtype=self.kv_dtype or None,
                               mesh=self.serving_mesh)
        # ---- shared-prefix KV reuse (radix index over full pages)
        if prefix_cache is None:
            prefix_cache = bool(_flag("FLAGS_decode_prefix_cache", True))
        self.prefix = PrefixCache(self.kv) if prefix_cache else None
        # ---- speculative decoding (draft proposes, target verifies)
        self.spec_k = int(spec_k if spec_k is not None
                          else _flag("FLAGS_decode_spec_k", 0))
        if draft_model is None:
            self.spec_k = 0
        self.draft: Optional[CachedDecoder] = None
        self._draft_k = self._draft_v = None
        if self.spec_k:
            if not supports_cached_decode(draft_model):
                raise TypeError("draft_model must support KV-cached "
                                "decode (forward(cache=) + "
                                "init_kv_pools)")
            dspec = draft_model.kv_cache_spec()
            if dspec["max_seq_len"] < self.max_seq_len:
                raise ValueError(
                    f"draft model max_seq_len={dspec['max_seq_len']} "
                    f"is shorter than the engine's "
                    f"max_seq_len={self.max_seq_len}")
            draft_model.eval()
            # the draft shares the target's block tables 1:1 (its own
            # pools, same page geometry), so prefix-cache hits reuse
            # draft K/V for free and rollback is the same truncation
            self.draft = CachedDecoder(
                draft_model, max_batch=self.max_batch,
                page_size=self.page_size,
                pages_per_seq=self.pages_per_seq, donate=donate,
                max_positions=self.max_seq_len,
                use_pallas=self.use_pallas, kv_dtype=self.kv_dtype,
                mesh=self.serving_mesh)
            self._draft_k, self._draft_v = draft_model.init_kv_pools(
                self.kv.num_pages, self.page_size,
                self.kv_dtype or None)
            self._draft_k, self._draft_v = \
                self.serving_mesh.place_pools(self._draft_k,
                                              self._draft_v)
        self.metrics = DecodeMetrics(name, self.max_batch,
                                     self.kv.capacity)
        self.metrics.set_kv_pages(0, self.kv.capacity)
        self.metrics.set_kv_pool_bytes(self.kv.pool_bytes(),
                                       self.kv_dtype)
        # ---- multi-tenant admission (scheduling subsystem): an
        # AdmissionController adds per-tenant token-bucket quotas,
        # weighted-fair queue ordering, and priority-aware
        # page-pressure preemption; None = classic FIFO engine
        self.scheduler = scheduler
        if scheduler is not None:
            from ..scheduling.schedz import register_controller
            register_controller(scheduler)
        # ONE Condition is both the engine lock and the wakeup channel
        self._lock = threading.Condition()
        self._queue: "deque[_Request]" = deque()
        self._slots: List[Optional[_ActiveSeq]] = [None] * self.max_batch
        self._tables = np.zeros((self.max_batch, self.pages_per_seq),
                                np.int32)
        self._closed = False
        self._abort = False
        self._loop_running = False
        self._worker: Optional[threading.Thread] = None
        self._steps = 0
        # readiness gate (mirrors InferenceServer): not-ready until a
        # warmup pass completes, so a fleet router skips cold engines
        self._ready_gate = bool(
            _flag("FLAGS_serving_ready_requires_warmup", False))
        self._warmed = threading.Event()
        self.telemetry = self._attach_telemetry(telemetry_port, name)
        self._manifest_recorded = set()
        self._manifest = self._init_manifest(name)
        if self._manifest is not None and len(self._manifest) and \
                bool(_flag("FLAGS_decode_warmup_from_manifest", False)):
            self.warmup_from_manifest()
        with _ENGINES_LOCK:
            _ENGINES.add(self)
        if start:
            self.start()

    # ------------------------------------------------------ plumbing
    def _attach_telemetry(self, telemetry_port, name):
        port = telemetry_port if telemetry_port is not None \
            else _flag("FLAGS_serving_telemetry_port", -1)
        if port is None or int(port) < 0:
            return None
        from ... import observability
        srv = observability.start_telemetry_server(port=int(port))
        observability.add_health_check(f"decode:{name}", self._health)
        observability.add_readiness_check(f"decode:{name}",
                                          self._readiness)
        return srv

    def _init_manifest(self, name):
        if not str(_flag("FLAGS_compile_cache_dir", "") or ""):
            return None
        try:
            from ...compile_cache import WarmupManifest, default_cache
            cache = default_cache()
            if cache is None:
                return None
            return WarmupManifest(WarmupManifest.default_path(
                cache.directory, f"decode-{name}",
                self.decoder.fingerprint()))
        except Exception:  # noqa: BLE001 - optimization artifact only
            return None

    @property
    def warmup_manifest(self):
        return self._manifest

    def _health(self):
        if self._closed:
            return False, "shut down"
        w = self._worker
        if w is not None and not w.is_alive() and not self._loop_running:
            return False, "worker thread died"
        return True, {"queue_depth": self.queue_depth,
                      "active_sequences": self.active_sequences}

    @property
    def ready(self) -> bool:
        """Traffic-readiness (see InferenceServer.ready): live, and —
        when the ``FLAGS_serving_ready_requires_warmup`` gate is on —
        warmed up."""
        if self._closed:
            return False
        return self._warmed.is_set() or not self._ready_gate

    def mark_ready(self):
        self._warmed.set()

    def _readiness(self):
        return self.ready, {"warmed": self._warmed.is_set(),
                            "gated": self._ready_gate}

    def refresh_params(self):
        """Re-snapshot the model's live parameters into the decode
        engine (no recompile — params are call operands). The fleet's
        in-process hot-swap path: update the model's weights, then
        ``refresh_params()``; subsequent prefills/decodes use the new
        weights while in-flight sequences keep streaming. Cached
        prefix pages hold K/V computed with the OLD weights, so the
        index is cleared — serving them to new-weight requests would
        be silent staleness."""
        self.decoder.refresh_params()
        if self.draft is not None:
            self.draft.refresh_params()
        self.clear_prefix_cache()

    def clear_prefix_cache(self) -> int:
        """Drop every unpinned cached prefix page back to the free
        list (pages shared with in-flight sequences stay until those
        finish). Returns the number of pages freed."""
        if self.prefix is None:
            return 0
        with self._lock:
            n = self.prefix.clear()
            if n:
                self.metrics.set_kv_pages(self.kv.used_pages,
                                          self.kv.free_pages)
            return n

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_sequences(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if s is not None)

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        with self._lock:
            if self.prefix is not None:
                snap["prefix"].update(self.prefix.stats())
            snap["spec"]["k"] = self.spec_k
            snap["kv_leak_check"] = self.kv.leak_check()
        return snap

    def statusz(self) -> dict:
        """One engine's /statusz section: page accounting (with the
        refcount-leak tripwire), prefix-cache and speculative state."""
        with self._lock:
            out = {
                "closed": self._closed,
                "queue_depth": len(self._queue),
                "active_sequences": sum(
                    1 for s in self._slots if s is not None),
                "kv_leak_check": self.kv.leak_check(),
                "spec_k": self.spec_k,
            }
            if self.serving_mesh.live:
                out["serving_mesh"] = self.serving_mesh.statusz(
                    kv_pool_bytes=self.kv.pool_bytes(),
                    num_heads=int(self.model.kv_cache_spec()
                                  ["num_heads"]))
            if self.prefix is not None:
                out["prefix_cache"] = self.prefix.stats()
            if self.scheduler is not None:
                depths: Dict[str, int] = {}
                for q in self._queue:
                    depths[q.tenant] = depths.get(q.tenant, 0) + 1
                out["tenant_queue_depth"] = depths
        return out

    # ------------------------------------------------------ lifecycle
    def start(self):
        with self._lock:
            if self._closed:
                raise ServerClosedError("engine already shut down")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._loop,
                    name=f"decode-{self.metrics.name}", daemon=True)
                self._worker.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None):
        """Stop accepting requests; ``drain`` (default) lets queued and
        in-flight sequences finish, otherwise both are failed with
        ServerClosedError. Idempotent."""
        with self._lock:
            self._closed = True
            if not drain:
                self._abort = True
            self._lock.notify_all()
        w = self._worker
        if w is not None and w.is_alive() and \
                w is not threading.current_thread():
            w.join(timeout)
        elif not self._loop_running:
            # never-started engine (start=False): run the loop inline so
            # queued requests still drain (or abort) instead of hanging
            # their futures forever
            self._loop()
        if self.telemetry is not None:
            from ...observability import (remove_health_check,
                                          remove_readiness_check)
            remove_health_check(f"decode:{self.metrics.name}")
            remove_readiness_check(f"decode:{self.metrics.name}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
        return False

    # ------------------------------------------------------ submission
    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        temperature: float = 0.0,
                        timeout_ms: Optional[float] = None,
                        seed: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        tenant: Optional[str] = None
                        ) -> StreamingFuture:
        """Enqueue one prompt; returns the token stream. ``timeout_ms``
        is a SCHEDULING deadline (like ``InferenceServer.submit``): a
        request still queued past it fails with DeadlineExceededError;
        once prefilled, the stream always runs to completion.
        ``deadline_ms`` is the HARD end-to-end budget (fleet deadline
        propagation): a stream still decoding past it is EVICTED at
        the next batch re-form — its pages return to the free list and
        its future fails with DeadlineExceededError (tokens already
        emitted stay available) — instead of burning decode steps on
        an answer nobody is waiting for. ``tenant`` selects the
        multi-tenant envelope when the engine has a scheduler
        (untagged maps to ``default``): over-quota submissions raise
        the typed per-tenant QuotaExceededError. Raises QueueFullError
        at capacity, ServerClosedError after shutdown, ValueError for
        prompts that leave no room to generate."""
        if self._closed:
            raise ServerClosedError("engine is shut down")
        prompt = np.asarray(
            prompt.numpy() if hasattr(prompt, "numpy") else prompt
        ).astype(np.int64).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size >= self.max_seq_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate within max_seq_len={self.max_seq_len}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        ctx = tracing.request_context()
        prio_rank, tname = 1, "default"
        if self.scheduler is not None:
            pol = self.scheduler.policy.lookup(tenant)
            tname, prio_rank = pol.tenant, pol.rank
            # token-denominated quota: one submission spends
            # prompt + generation budget from the tenant's bucket
            cost = float(prompt.size + max_new_tokens)
            if not self.scheduler.try_admit(tname, cost):
                self.metrics.count("rejected")
                err = QuotaExceededError(
                    f"tenant {tname!r} exceeded its token quota "
                    f"({pol.rate:g}/s, burst {pol.burst:g})",
                    tenant=tname)
                if ctx is not None:
                    tracing.record_span(
                        ctx.child(), "generate::shed", stage="shed",
                        start_unix_ns=time.time_ns(), duration_ms=0.0,
                        status="error",
                        attrs={"server": self.metrics.name,
                               "tenant": tname,
                               "error": "QuotaExceededError"},
                        root=True)
                raise err
        req = _Request(prompt, max_new_tokens, temperature, seed,
                       timeout_ms if timeout_ms is not None
                       else self.default_timeout_ms,
                       trace=ctx.child() if ctx is not None else None,
                       deadline_ms=deadline_ms,
                       tenant=tname, prio_rank=prio_rank)
        with self._lock:
            if self._closed:
                raise ServerClosedError("engine is shut down")
            if len(self._queue) >= self.queue_capacity:
                self.metrics.count("rejected")
                if req.trace is not None:
                    tracing.record_span(
                        req.trace, "generate::shed", stage="shed",
                        start_unix_ns=req.t_wall_ns, duration_ms=0.0,
                        status="error",
                        attrs={"server": self.metrics.name,
                               "error": "QueueFullError"}, root=True)
                raise QueueFullError(
                    f"generation queue at capacity "
                    f"({self.queue_capacity})")
            self._queue.append(req)
            self.metrics.count("submitted")
            self._lock.notify_all()
        return req.future

    def generate(self, prompt, max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 timeout_ms: Optional[float] = None,
                 seed: Optional[int] = None) -> List[int]:
        """Synchronous convenience: submit and block for the full
        generated-token list."""
        return self.submit_generate(
            prompt, max_new_tokens, temperature, timeout_ms,
            seed).result()

    # ------------------------------------------------------ warmup
    def warmup(self, seq_buckets: Optional[Sequence[int]] = None,
               batch_buckets: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the decode lattice: the single decode-step shape
        (plus the verify step under speculation) and every (pow2-row,
        seq-bucket) prefill shape admission can dispatch — continuous
        batching prefills PARTIAL row groups as slots churn, so the
        row ladder matters, not just max_batch. With the prefix cache
        on, the chunked (suffix-prefill) lattice is warmed alongside,
        and a draft model's mirror signatures ride every warm. Returns
        the number of fresh signatures."""
        fresh = self._warm_decode()
        if self.spec_k:
            fresh += self._warm_verify()
        seqs = list(seq_buckets if seq_buckets is not None
                    else (self.policy.seq_buckets or []))
        if batch_buckets is None:
            batch_buckets, r = [], 1
            while r < self.max_batch:
                batch_buckets.append(r)
                r <<= 1
            batch_buckets.append(self.max_batch)
        for s in seqs:
            for r in batch_buckets:
                fresh += self._warm_prefill(int(r), int(s))
                if self.prefix is not None:
                    fresh += self._warm_chunked(int(r), int(s))
        self._warmed.set()
        return fresh

    def _warm_decode(self) -> int:
        args = (np.zeros(self.max_batch, np.int64),
                np.zeros(self.max_batch, np.int32),
                np.zeros(self.max_batch, bool),
                np.zeros(self.max_batch, np.int32),
                np.zeros_like(self._tables))
        logits, k2, v2, fresh = self.decoder.decode(
            *args, self.kv.k, self.kv.v)
        np.asarray(logits)
        self.kv.k, self.kv.v = k2, v2
        self._note_dispatch("generate_decode", fresh, [
            ((self.max_batch,), "int64"), ((self.max_batch,), "int32"),
            ((self.max_batch,), "bool"), ((self.max_batch,), "int32"),
            (self._tables.shape, "int32")], record=False)
        fresh = int(fresh)
        if self.draft is not None:
            dlogits, dk, dv, dfresh = self.draft.decode(
                *args, self._draft_k, self._draft_v)
            np.asarray(dlogits)
            self._draft_k, self._draft_v = dk, dv
            self.metrics.observe_compile(hit=not dfresh)
            fresh += int(dfresh)
        return fresh

    def _warm_prefill(self, rows: int, seq: int) -> int:
        ids = np.zeros((rows, seq), np.int64)
        lens = np.zeros(rows, np.int32)
        tables = np.zeros((rows, self.pages_per_seq), np.int32)
        last, k2, v2, fresh = self.decoder.prefill(
            ids, lens, tables, self.kv.k, self.kv.v)
        np.asarray(last)
        self.kv.k, self.kv.v = k2, v2
        self._note_dispatch("generate_prefill", fresh, [
            (ids.shape, "int64"), (lens.shape, "int32"),
            (tables.shape, "int32")], record=False)
        fresh = int(fresh)
        if self.draft is not None:
            dlast, dk, dv, dfresh = self.draft.prefill(
                ids, lens, tables, self._draft_k, self._draft_v)
            np.asarray(dlast)
            self._draft_k, self._draft_v = dk, dv
            self.metrics.observe_compile(hit=not dfresh)
            fresh += int(dfresh)
        return fresh

    def _warm_chunked(self, rows: int, seq: int) -> int:
        ids = np.zeros((rows, seq), np.int64)
        start = np.zeros(rows, np.int32)
        seg = np.zeros(rows, np.int32)
        tables = np.zeros((rows, self.pages_per_seq), np.int32)
        last, k2, v2, fresh = self.decoder.prefill_chunked(
            ids, start, seg, tables, self.kv.k, self.kv.v)
        np.asarray(last)
        self.kv.k, self.kv.v = k2, v2
        self._note_dispatch("generate_chunked", fresh, [
            (ids.shape, "int64"), (start.shape, "int32"),
            (seg.shape, "int32"), (tables.shape, "int32")],
            record=False)
        fresh = int(fresh)
        if self.draft is not None:
            dlast, dk, dv, dfresh = self.draft.prefill_chunked(
                ids, start, seg, tables, self._draft_k, self._draft_v)
            np.asarray(dlast)
            self._draft_k, self._draft_v = dk, dv
            self.metrics.observe_compile(hit=not dfresh)
            fresh += int(dfresh)
        return fresh

    def _warm_verify(self) -> int:
        """The ONE [max_batch, spec_k + 1] verify signature (site-
        tagged in the manifest so a restarted engine replays it)."""
        width = self.spec_k + 1
        ids = np.zeros((self.max_batch, width), np.int64)
        start = np.zeros(self.max_batch, np.int32)
        seg = np.zeros(self.max_batch, np.int32)
        tables = np.zeros_like(self._tables)
        logits, k2, v2, fresh = self.decoder.verify(
            ids, start, seg, tables, self.kv.k, self.kv.v)
        np.asarray(logits)
        self.kv.k, self.kv.v = k2, v2
        self._note_dispatch("generate_verify", fresh, [
            (ids.shape, "int64"), (start.shape, "int32"),
            (seg.shape, "int32"), (tables.shape, "int32")],
            record=False)
        return int(fresh)

    def warmup_from_manifest(self, path: Optional[str] = None) -> int:
        """Replay the persisted decode/prefill signatures a previous
        process dispatched — each a persistent-cache load when
        ``FLAGS_compile_cache_dir`` is warm. Returns the fresh-compile
        count; 0 when no manifest exists."""
        if path is not None:
            from ...compile_cache import WarmupManifest
            manifest = WarmupManifest(path)
        else:
            manifest = self._manifest
        if manifest is None:
            return 0
        fresh = 0
        for spec in manifest.specs(site="generate_prefill"):
            (rows, seq) = spec["feeds"][0][0]
            if rows > self.max_batch or seq > self.max_seq_len:
                continue
            fresh += self._warm_prefill(int(rows), int(seq))
        for spec in manifest.specs(site="generate_chunked"):
            (rows, seq) = spec["feeds"][0][0]
            if rows > self.max_batch or seq > self.max_seq_len:
                continue
            fresh += self._warm_chunked(int(rows), int(seq))
        if manifest.specs(site="generate_decode"):
            fresh += self._warm_decode()
        if self.spec_k and any(
                spec["feeds"][0][0] == (self.max_batch, self.spec_k + 1)
                for spec in manifest.specs(site="generate_verify")):
            fresh += self._warm_verify()
        self._warmed.set()
        return fresh

    def _note_dispatch(self, site: str, fresh: bool, feeds,
                       record: bool = True):
        """Compile accounting per dispatch; TRAFFIC dispatches (not
        warmup replays) persist their signature so a restarted engine
        pre-warms exactly the observed lattice."""
        self.metrics.observe_compile(hit=not fresh)
        if record and self._manifest is not None:
            key = (site, tuple(tuple(s) for s, _ in feeds))
            if key not in self._manifest_recorded:
                self._manifest_recorded.add(key)
                self._manifest.record(feeds, site=site)

    # ------------------------------------------------------ worker
    def _loop(self):
        with self._lock:
            self._loop_running = True
        try:
            while True:
                self._admit_and_prefill()
                with self._lock:
                    self._evict_expired_streams()
                    active = [s for s in self._slots if s is not None]
                    if self._abort:
                        self._do_abort()
                        return
                    if not active:
                        if self._closed and not self._queue:
                            return
                        self._lock.wait(0.05)
                        continue
                if self.draft is not None:
                    self._spec_iteration(active)
                else:
                    self._decode_iteration(active)
        finally:
            with self._lock:
                self._loop_running = False

    def _evict_expired_streams(self):
        """Deadline check at batch re-form (lock held): an in-flight
        stream whose HARD budget expired is evicted now — its future
        fails with DeadlineExceededError (emitted tokens stay
        readable), its pages return to the free list, and its lane
        frees up for the admission pass — instead of spending further
        decode steps on a request whose caller has already given up."""
        now = time.monotonic()
        for seq in list(self._slots):
            if seq is None or not seq.req.hard_expired(now):
                continue
            seq.req.future._fail(
                DeadlineExceededError(
                    f"deadline budget expired after "
                    f"{seq.n_generated} generated token(s); stream "
                    f"evicted"), reason="deadline")
            self._release(seq, "timed_out")
            self._trace_finish([seq], "error",
                               error="DeadlineExceededError",
                               finish_reason="deadline")

    def _preempt_for_pages(self, rank: int, need: int) -> bool:
        """Priority-aware page pressure (lock held): park in-flight
        streams of a STRICTLY lower priority class (higher rank
        number) — lowest class first, youngest first within a class —
        until ``need`` pages are free. Generalizes the expired-stream
        eviction above: pages go back to the free list (leak_check
        stays clean), but the stream is re-queued to RESUME from its
        full token history instead of failing. Returns True when the
        reservation now fits; equal-or-higher classes are never
        touched."""
        if need > self.kv.free_pages + sum(
                len(s.pages) for s in self._slots
                if s is not None and s.req.prio_rank > rank):
            return False        # not even parking everyone would fit
        victims = [s for s in self._slots
                   if s is not None and s.req.prio_rank > rank]
        victims.sort(key=lambda s: (-s.req.prio_rank,
                                    -s.req.submit_t))
        for seq in victims:
            if self.kv.free_pages >= need:
                break
            self._park(seq)
        return self.kv.free_pages >= need

    def _park(self, seq: _ActiveSeq):
        """Preempt ONE in-flight stream (lock held): free its pages
        and lane, then re-queue it to resume — the resumed request's
        prompt is the full token history, so a later prefill (prefix
        cache permitting, a cheap one) reconstructs the K/V and the
        SAME future keeps streaming where it left off. When resume is
        impossible (engine closing, queue full, or the history already
        fills max_seq_len) the stream fails with the typed per-tenant
        QuotaExceededError instead of hanging."""
        self._release(seq, "parked")
        r = seq.req
        history = list(seq.history)     # prompt + every emitted token
        resumable = (not self._closed
                     and len(self._queue) < self.queue_capacity
                     and len(history) < self.max_seq_len
                     and r.max_new - seq.n_generated >= 1)
        if not resumable:
            self.metrics.count("preempted")
            r.future._fail(
                QuotaExceededError(
                    f"stream preempted by a higher priority class "
                    f"after {seq.n_generated} token(s); resume "
                    f"unavailable", tenant=r.tenant),
                reason="preempted")
            self._trace_finish([seq], "error",
                               error="QuotaExceededError",
                               finish_reason="preempted")
            return
        nr = _Request(np.asarray(history, np.int64),
                      r.max_new - seq.n_generated, r.temperature,
                      None, None, trace=r.trace,
                      tenant=r.tenant, prio_rank=r.prio_rank,
                      n_done=r.n_done + seq.n_generated)
        # the resumed request IS the original request: same future,
        # same RNG stream, same deadlines, same submit time (so the
        # scheduling deadline keeps covering the whole stream)
        nr.future = r.future
        nr.rng = r.rng
        nr.submit_t = r.submit_t
        nr.deadline = r.deadline
        nr.hard_deadline = r.hard_deadline
        nr.t_wall_ns = r.t_wall_ns
        self._queue.append(nr)

    def _do_abort(self):
        """drain=False shutdown: fail everything still live (lock
        held)."""
        err = ServerClosedError("engine shut down before completion")
        for req in self._queue:
            req.future._fail(err, reason="shutdown")
            self.metrics.count("failed")
        self._queue.clear()
        for seq in list(self._slots):
            if seq is not None:
                seq.req.future._fail(err, reason="shutdown")
                self._release(seq, "failed")
                self._trace_finish([seq], "error",
                                   error="ServerClosedError")

    # ---- admission + prefill ----
    def _admit_and_prefill(self):
        admitted: List[_ActiveSeq] = []
        now = time.monotonic()
        with self._lock:
            # deadline sweep over the whole queue (it is bounded)
            live = deque()
            for req in self._queue:
                if req.expired(now):
                    self.metrics.count("timed_out")
                    req.future._fail(
                        DeadlineExceededError(
                            "deadline passed before the request could "
                            "be scheduled"), reason="timed_out")
                    if req.trace is not None:
                        tracing.record_span(
                            req.trace, "generate::queue",
                            stage="queue",
                            start_unix_ns=req.t_wall_ns,
                            duration_ms=(now - req.submit_t) * 1e3,
                            status="error",
                            attrs={"server": self.metrics.name,
                                   "error": "DeadlineExceededError"},
                            root=True)
                else:
                    live.append(req)
            self._queue = live
            free_slots = [i for i, s in enumerate(self._slots)
                          if s is None]
            while self._queue and free_slots:
                # weighted-fair pick across tenants (priority classes
                # first) when a scheduler is attached; FIFO otherwise
                idx = 0
                if self.scheduler is not None and len(self._queue) > 1:
                    sel = self.scheduler.select(self._queue)
                    idx = sel if sel is not None else 0
                req = self._queue[idx]
                max_total = min(len(req.prompt) + req.max_new,
                                self.max_seq_len)
                # admission consults the prefix index FIRST: matched
                # full pages are shared (retained), only the remainder
                # of the reservation comes from the free list
                matched, shared = (0, [])
                if self.prefix is not None:
                    matched, shared = self.prefix.match(req.prompt)
                need = self.kv.pages_for(max_total) - len(shared)
                pages = self.kv.alloc(need)
                if pages is None and self.prefix is not None:
                    # pool pressure: reclaim LRU cache-only pages,
                    # then retry once
                    if self.prefix.evict(need - self.kv.free_pages):
                        pages = self.kv.alloc(need)
                if pages is None and self.scheduler is not None:
                    # priority-aware page pressure: park strictly
                    # LOWER-priority in-flight streams (batch before
                    # standard; realtime is never touched) until the
                    # reservation fits, then retry once
                    if self._preempt_for_pages(req.prio_rank, need):
                        pages = self.kv.alloc(need)
                if pages is None:
                    break       # head-of-line until pages free up
                # exception barrier (pdlint RP001): between taking the
                # reservation and publishing it into self._slots no
                # failure may keep the references — a leaked page never
                # returns to the free list and admission wedges once
                # the pool drains
                try:
                    self.kv.retain(shared)
                except BaseException:
                    self.kv.release(pages)
                    raise
                try:
                    if self.prefix is not None:
                        self.prefix.note_admission(matched)
                        if matched:
                            self.metrics.observe_prefix_hit(matched)
                    del self._queue[idx]
                    slot = free_slots.pop(0)
                    seq = _ActiveSeq(req, slot, shared + pages,
                                     max_total, prefix_len=matched)
                    self._slots[slot] = seq
                except BaseException:
                    self.kv.release(shared + pages)
                    raise
                self._tables[slot, :] = 0
                self._tables[slot, :len(seq.pages)] = seq.pages
                admitted.append(seq)
            if admitted:
                self.metrics.set_kv_pages(self.kv.used_pages,
                                          self.kv.free_pages)
        if not admitted:
            return
        t_adm = time.time_ns()
        for seq in admitted:
            if seq.req.trace is not None:
                tracing.record_span(
                    seq.req.trace, "generate::queue", stage="queue",
                    start_unix_ns=seq.req.t_wall_ns,
                    duration_ms=max(
                        0.0, (t_adm - seq.req.t_wall_ns) / 1e6),
                    attrs={"server": self.metrics.name,
                           "slot": seq.slot,
                           "pages": len(seq.pages)})
        # prefill OUTSIDE the lock: cold prompts grouped by prompt seq
        # bucket (windowed causal attention), prefix hits grouped by
        # SUFFIX bucket (chunked attention over the cached prefix) —
        # the TTFT win is the suffix window being a fraction of the
        # prompt window
        cold: Dict[int, List[_ActiveSeq]] = {}
        hot: Dict[int, List[_ActiveSeq]] = {}
        for seq in admitted:
            n_suffix = len(seq.req.prompt) - seq.prefix_len
            if seq.prefix_len:
                bucket = min(self.policy.bucket_seq(n_suffix),
                             self.max_seq_len)
                hot.setdefault(bucket, []).append(seq)
            else:
                bucket = min(self.policy.bucket_seq(n_suffix),
                             self.max_seq_len)
                cold.setdefault(bucket, []).append(seq)
        for bucket, seqs in cold.items():
            self._prefill_group(seqs, bucket)
        for bucket, seqs in hot.items():
            self._prefill_chunked_group(seqs, bucket)

    def _prefill_group(self, seqs: List[_ActiveSeq], seq_bucket: int):
        rows = len(seqs)
        padded = min(self.policy.bucket_batch(rows), self.max_batch)
        ids = np.full((padded, seq_bucket), self.pad_token_id, np.int64)
        lens = np.zeros(padded, np.int32)
        tables = np.zeros((padded, self.pages_per_seq), np.int32)
        for i, seq in enumerate(seqs):
            p = seq.req.prompt
            ids[i, :len(p)] = p
            lens[i] = len(p)
            tables[i] = self._tables[seq.slot]
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        try:
            last, k2, v2, fresh = self.decoder.prefill(
                ids, lens, tables, self.kv.k, self.kv.v)
            logits = np.asarray(last)
            self.kv.k, self.kv.v = k2, v2
            if self.draft is not None:
                dlast, dk, dv, dfresh = self.draft.prefill(
                    ids, lens, tables, self._draft_k, self._draft_v)
                np.asarray(dlast)
                self._draft_k, self._draft_v = dk, dv
                self.metrics.observe_compile(hit=not dfresh)
        except Exception as e:  # noqa: BLE001 - fault barrier: fail
            # only THIS group's requests; the worker survives
            with self._lock:
                for seq in seqs:
                    seq.req.future._fail(e)
                    self._release(seq, "failed")
            self._trace_finish(seqs, "error",
                               error=f"{type(e).__name__}: {e}")
            return
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe_step("prefill", ms)
        try:
            # stepprof envelope per prefill group: joins with the
            # generate_prefill executable for paddle_mfu{kind=prefill}
            from ...observability.stepprof import default_profiler
            default_profiler().record_step(
                ms, kind="prefill", step=self._steps,
                device_ms=ms, occupancy=rows,
                kv_pages_used=self.kv.used_pages)
        except Exception:  # noqa: BLE001 - profiling is garnish
            pass
        for seq in seqs:
            if seq.req.trace is not None:
                tracing.record_span(
                    seq.req.trace, "generate::prefill",
                    stage="prefill", start_unix_ns=t_wall,
                    duration_ms=ms,
                    attrs={"server": self.metrics.name,
                           "rows": rows, "seq_bucket": seq_bucket,
                           "prefix_hit": False, "tokens_reused": 0,
                           "compile_miss": bool(fresh)})
        self._note_dispatch("generate_prefill", fresh, [
            (ids.shape, "int64"), (lens.shape, "int32"),
            (tables.shape, "int32")])
        self._publish_prompts(seqs)
        self._sample_and_emit(seqs, logits[:rows])

    def _prefill_chunked_group(self, seqs: List[_ActiveSeq],
                               seq_bucket: int):
        """Suffix prefill for prefix-cache hits: the window holds only
        each prompt's unmatched tail; attention reaches the shared
        prefix pages through the block tables (kind="chunked")."""
        rows = len(seqs)
        padded = min(self.policy.bucket_batch(rows), self.max_batch)
        ids = np.full((padded, seq_bucket), self.pad_token_id, np.int64)
        start = np.zeros(padded, np.int32)
        seg = np.zeros(padded, np.int32)
        tables = np.zeros((padded, self.pages_per_seq), np.int32)
        for i, seq in enumerate(seqs):
            suffix = seq.req.prompt[seq.prefix_len:]
            ids[i, :len(suffix)] = suffix
            start[i] = seq.prefix_len
            seg[i] = len(suffix)
            tables[i] = self._tables[seq.slot]
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        try:
            last, k2, v2, fresh = self.decoder.prefill_chunked(
                ids, start, seg, tables, self.kv.k, self.kv.v)
            logits = np.asarray(last)
            self.kv.k, self.kv.v = k2, v2
            if self.draft is not None:
                dlast, dk, dv, dfresh = self.draft.prefill_chunked(
                    ids, start, seg, tables,
                    self._draft_k, self._draft_v)
                np.asarray(dlast)
                self._draft_k, self._draft_v = dk, dv
                self.metrics.observe_compile(hit=not dfresh)
        except Exception as e:  # noqa: BLE001 - fault barrier, as above
            with self._lock:
                for seq in seqs:
                    seq.req.future._fail(e)
                    self._release(seq, "failed")
            self._trace_finish(seqs, "error",
                               error=f"{type(e).__name__}: {e}")
            return
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe_step("prefill", ms)
        try:
            # envelope for the suffix-prefill step (same prefill kind
            # as the cold path: one MFU stream per step kind)
            from ...observability.stepprof import default_profiler
            default_profiler().record_step(
                ms, kind="prefill", step=self._steps,
                device_ms=ms, occupancy=rows,
                kv_pages_used=self.kv.used_pages)
        except Exception:  # noqa: BLE001 - profiling is garnish
            pass
        for seq in seqs:
            if seq.req.trace is not None:
                tracing.record_span(
                    seq.req.trace, "generate::prefill",
                    stage="prefill", start_unix_ns=t_wall,
                    duration_ms=ms,
                    attrs={"server": self.metrics.name,
                           "rows": rows, "seq_bucket": seq_bucket,
                           "prefix_hit": True,
                           "tokens_reused": seq.prefix_len,
                           "compile_miss": bool(fresh)})
        self._note_dispatch("generate_chunked", fresh, [
            (ids.shape, "int64"), (start.shape, "int32"),
            (seg.shape, "int32"), (tables.shape, "int32")])
        self._publish_prompts(seqs)
        self._sample_and_emit(seqs, logits[:rows])

    def _publish_prompts(self, seqs: List[_ActiveSeq]):
        """Index each prefilled prompt's FULL pages so later admissions
        (including in-flight concurrency) can share them. Runs only
        after the prefill that wrote the pages — and the draft mirror,
        when speculation is on — completed, so indexed pages always
        hold valid K/V in every pool."""
        if self.prefix is None:
            return
        with self._lock:
            for seq in seqs:
                self.prefix.publish(seq.req.prompt, seq.pages,
                                    n_tokens=len(seq.req.prompt))
                seq.published = True

    # ---- one decode iteration ----
    def _decode_iteration(self, active: List[_ActiveSeq]):
        tokens = np.zeros(self.max_batch, np.int64)
        positions = np.zeros(self.max_batch, np.int32)
        mask = np.zeros(self.max_batch, bool)
        ctx_after = np.zeros(self.max_batch, np.int32)
        for seq in active:
            tokens[seq.slot] = seq.last_token
            positions[seq.slot] = seq.ctx
            mask[seq.slot] = True
            ctx_after[seq.slot] = seq.ctx + 1
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        try:
            logits, k2, v2, fresh = self.decoder.decode(
                tokens, positions, mask, ctx_after, self._tables,
                self.kv.k, self.kv.v)
            logits = np.asarray(logits)
        except Exception as e:  # noqa: BLE001 - fault barrier: a model
            # error fails the in-flight sequences, not the engine
            with self._lock:
                for seq in active:
                    seq.req.future._fail(e)
                    self._release(seq, "failed")
            self._trace_finish(active, "error",
                               error=f"{type(e).__name__}: {e}")
            return
        self.kv.k, self.kv.v = k2, v2
        ms = (time.perf_counter() - t0) * 1e3
        self._steps += 1
        self.metrics.observe_step("decode", ms)
        self.metrics.observe_occupancy(len(active))
        try:
            # continuous step profiler: one envelope per decode
            # iteration (occupancy + KV pressure ride along); a
            # straggler iteration becomes an error span in /tracez
            from ...observability.stepprof import default_profiler
            default_profiler().record_step(
                ms, kind="decode", step=self._steps,
                device_ms=ms, occupancy=len(active),
                kv_pages_used=self.kv.used_pages,
                attrs={"prefix_tokens_reused":
                       self.prefix.tokens_reused
                       if self.prefix is not None else 0})
        except Exception:  # noqa: BLE001 - profiling is garnish on the
            pass           # decode hot path
        for seq in active:
            if seq.req.trace is not None:
                # per-iteration span; long streams are bounded by the
                # flight recorder's per-trace cap, not here
                tracing.record_span(
                    seq.req.trace, "generate::decode_step",
                    stage="decode_step", start_unix_ns=t_wall,
                    duration_ms=ms,
                    attrs={"server": self.metrics.name,
                           "step": seq.n_generated,
                           "occupancy": len(active)})
        self._note_dispatch("generate_decode", fresh, [
            ((self.max_batch,), "int64"), ((self.max_batch,), "int32"),
            ((self.max_batch,), "bool"), ((self.max_batch,), "int32"),
            (self._tables.shape, "int32")])
        for seq in active:
            seq.ctx += 1
        self._sample_and_emit(active,
                              logits[[s.slot for s in active]])

    # ---- one speculative iteration: draft proposes, target verifies
    def _spec_iteration(self, active: List[_ActiveSeq]):
        """Draft-then-verify (Leviathan et al.): the draft model
        proposes ``spec_k`` tokens per lane through its own paged pools
        (same block tables), then the target scores the whole
        ``[last_accepted, d_1..d_k]`` window in ONE fixed-shape
        ``[max_batch, k + 1]`` verify step. Accept-and-resample on the
        host keeps the output distribution identical to plain
        sampling; rejected tokens' K/V writes sit on the lane's
        already-reserved pages and are rolled back by truncating
        ``ctx``/``draft_ctx`` — the pool itself is never mutated."""
        b, k = self.max_batch, self.spec_k
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        try:
            draft_toks, draft_probs = self._draft_propose(active, k)
            draft_ms = (time.perf_counter() - t0) * 1e3
            # ---- verify: one chunked window per lane
            ids = np.zeros((b, k + 1), np.int64)
            start = np.zeros(b, np.int32)
            seg = np.zeros(b, np.int32)
            for s in active:
                ids[s.slot, 0] = s.last_token
                ids[s.slot, 1:] = draft_toks[s.slot]
                start[s.slot] = s.ctx
                seg[s.slot] = k + 1
            vlogits, k2, v2, fresh = self.decoder.verify(
                ids, start, seg, self._tables, self.kv.k, self.kv.v)
            vlogits = np.asarray(vlogits)
        except Exception as e:  # noqa: BLE001 - fault barrier: a model
            # error fails the in-flight sequences, not the engine
            with self._lock:
                for seq in active:
                    seq.req.future._fail(e)
                    self._release(seq, "failed")
            self._trace_finish(active, "error",
                               error=f"{type(e).__name__}: {e}")
            return
        self.kv.k, self.kv.v = k2, v2
        ms = (time.perf_counter() - t0) * 1e3
        self._steps += 1
        self.metrics.observe_step("decode", ms)
        self.metrics.observe_occupancy(len(active))
        self._note_dispatch("generate_verify", fresh, [
            (ids.shape, "int64"), (start.shape, "int32"),
            (seg.shape, "int32"), (self._tables.shape, "int32")])
        # ---- accept-and-resample per lane (host)
        toks_lists: List[List[int]] = []
        accs: List[int] = []
        n_accepted = 0
        for s in active:
            remaining = min(s.req.max_new - s.n_generated,
                            s.max_total - s.ctx)
            emitted, acc = accept_tokens(
                vlogits[s.slot], draft_toks[s.slot],
                draft_probs.get(s.slot), s.req.temperature, s.req.rng,
                max_emit=remaining,
                eos_token_id=self.eos_token_id)
            self.metrics.observe_spec(k, acc)
            n_accepted += acc
            s.ctx += len(emitted)
            # rollback-by-truncation: positions past the accepted
            # stream hold rejected garbage in both pools; the shrunken
            # ctx masks them and the next write overwrites in place
            s.draft_ctx = min(s.draft_ctx, s.ctx)
            toks_lists.append(emitted)
            accs.append(acc)
        try:
            from ...observability.stepprof import default_profiler
            default_profiler().record_step(
                ms, kind="decode", step=self._steps,
                device_ms=ms, occupancy=len(active),
                kv_pages_used=self.kv.used_pages,
                attrs={"spec_proposed": k * len(active),
                       "spec_accepted": n_accepted,
                       "prefix_tokens_reused":
                       self.prefix.tokens_reused
                       if self.prefix is not None else 0})
            # the verify window alone (iteration minus draft proposal)
            # as its own kind: joins with the generate_verify
            # executable for paddle_mfu{kind=verify}
            default_profiler().record_step(
                max(ms - draft_ms, 0.0), kind="verify",
                step=self._steps, occupancy=len(active),
                attrs={"draft_ms": round(draft_ms, 4)})
        except Exception:  # noqa: BLE001 - profiling is garnish
            pass
        for seq, toks, acc in zip(active, toks_lists, accs):
            if seq.req.trace is not None:
                tracing.record_span(
                    seq.req.trace, "generate::verify",
                    stage="verify", start_unix_ns=t_wall,
                    duration_ms=ms,
                    attrs={"server": self.metrics.name,
                           "proposed": k, "accepted": acc,
                           "emitted": len(toks),
                           "draft_ms": round(draft_ms, 3),
                           "occupancy": len(active)})
        self._emit_batch(active, toks_lists)

    def _draft_propose(self, active: List[_ActiveSeq], k: int):
        """Run the draft model ``k`` single-token steps (same
        [max_batch, 1] signature each time), sampling each lane's
        proposal from the draft distribution with the request's own
        RNG. Lanes whose draft pool lags the target context (one
        position, after a fully-accepted round) catch up first with
        masked feed steps. Returns ``(draft_toks [B, k] int64,
        {slot: draft_probs [k, vocab]} for sampled lanes)``."""
        b = self.max_batch
        while True:
            lag = [s for s in active if s.draft_ctx < s.ctx]
            if not lag:
                break
            tokens = np.zeros(b, np.int64)
            positions = np.zeros(b, np.int32)
            mask = np.zeros(b, bool)
            ctx_after = np.zeros(b, np.int32)
            for s in lag:
                tokens[s.slot] = s.history[s.draft_ctx]
                positions[s.slot] = s.draft_ctx
                mask[s.slot] = True
                ctx_after[s.slot] = s.draft_ctx + 1
            _, dk, dv, dfresh = self.draft.decode(
                tokens, positions, mask, ctx_after, self._tables,
                self._draft_k, self._draft_v)
            self._draft_k, self._draft_v = dk, dv
            self.metrics.observe_compile(hit=not dfresh)
            for s in lag:
                s.draft_ctx += 1
        draft_toks = np.zeros((b, k), np.int64)
        draft_probs: Dict[int, np.ndarray] = {}
        feed = np.zeros(b, np.int64)
        for s in active:
            feed[s.slot] = s.last_token
        for j in range(k):
            positions = np.zeros(b, np.int32)
            mask = np.zeros(b, bool)
            ctx_after = np.zeros(b, np.int32)
            for s in active:
                positions[s.slot] = s.draft_ctx
                mask[s.slot] = True
                ctx_after[s.slot] = s.draft_ctx + 1
            logits, dk, dv, dfresh = self.draft.decode(
                feed, positions, mask, ctx_after, self._tables,
                self._draft_k, self._draft_v)
            logits = np.asarray(logits)
            self._draft_k, self._draft_v = dk, dv
            self.metrics.observe_compile(hit=not dfresh)
            for s in active:
                row = logits[s.slot]
                if s.req.temperature > 0.0:
                    p = softmax(row, s.req.temperature)
                    probs = draft_probs.setdefault(
                        s.slot, np.zeros((k, row.shape[-1])))
                    probs[j] = p
                    cdf = np.cumsum(p)
                    tok = int(min(
                        np.searchsorted(
                            cdf, s.req.rng.random_sample() * cdf[-1],
                            side="right"),
                        row.shape[-1] - 1))
                else:
                    tok = int(row.argmax())
                draft_toks[s.slot, j] = tok
                feed[s.slot] = tok
                s.draft_ctx += 1
        return draft_toks, draft_probs

    # ---- shared harvest: sample, stream, evict ----
    def _sample_and_emit(self, seqs: List[_ActiveSeq],
                         logits: np.ndarray):
        temps = np.array([s.req.temperature for s in seqs], np.float64)
        uniforms = np.array([s.req.rng.random_sample() for s in seqs])
        toks = sample_next_tokens(logits, temps, uniforms=uniforms)
        self._emit_batch(seqs, [[int(t)] for t in toks])

    def _emit_batch(self, seqs: List[_ActiveSeq],
                    toks_lists: List[List[int]]):
        """Stream each sequence's newly-selected tokens (one from a
        prefill/decode step, up to spec_k + 1 from a verify step),
        then run the finish checks. Callers updated ``seq.ctx`` first."""
        now = time.monotonic()
        inter = []
        total = sum(len(t) for t in toks_lists)
        self.metrics.observe_tokens(total)
        with self._lock:
            for seq, toks in zip(seqs, toks_lists):
                for tok in toks:
                    tok = int(tok)
                    seq.last_token = tok
                    seq.history.append(tok)
                    seq.n_generated += 1
                    if seq.n_generated == 1:
                        if seq.req.n_done:
                            # a parked stream came back: count the
                            # resume, don't re-observe TTFT (its first
                            # token happened before the preemption)
                            self.metrics.count("resumed")
                        else:
                            self.metrics.observe_ttft(
                                (now - seq.req.submit_t) * 1e3)
                    else:
                        inter.append((now - seq.last_emit_t) * 1e3)
                    seq.last_emit_t = now
                    seq.req.future._emit(tok)
                if not toks:
                    continue
                if seq.req.future._cancel_requested:
                    seq.req.future._finish("cancelled")
                    self._release(seq, "cancelled")
                    self._trace_finish([seq], "ok",
                                       finish_reason="cancelled")
                elif self.eos_token_id is not None and \
                        int(toks[-1]) == self.eos_token_id:
                    seq.req.future._finish("eos")
                    self._release(seq, "completed")
                    self._trace_finish([seq], "ok",
                                       finish_reason="eos")
                elif seq.n_generated >= seq.req.max_new or \
                        seq.ctx + 1 > seq.max_total:
                    # ctx + 1: emitting one more token would need a
                    # cache slot past this sequence's reservation
                    seq.req.future._finish("length")
                    self._release(seq, "completed")
                    self._trace_finish([seq], "ok",
                                       finish_reason="length")
        if inter:
            self.metrics.observe_inter_token(inter)

    def _trace_finish(self, seqs: List[_ActiveSeq], status: str,
                      finish_reason: Optional[str] = None,
                      error: Optional[str] = None):
        """Record each traced sequence's ``generate::request`` root
        span (the whole-stream envelope). Error status tail-promotes
        unsampled traces."""
        now = time.time_ns()
        for seq in seqs:
            r = seq.req
            if r.trace is None:
                continue
            attrs = {"server": self.metrics.name,
                     "prompt_tokens": len(r.prompt),
                     "tokens": seq.n_generated}
            if finish_reason:
                attrs["finish_reason"] = finish_reason
            if error:
                attrs["error"] = error
            tracing.record_span(
                r.trace, "generate::request", stage="request",
                start_unix_ns=r.t_wall_ns,
                duration_ms=max(0.0, (now - r.t_wall_ns) / 1e6),
                status=status, attrs=attrs, root=True)

    def _release(self, seq: _ActiveSeq, event: str):
        """Evict one sequence: drop its page references, free the slot
        (lock held). A COMPLETED sequence first publishes its full
        pages — prompt AND generated tokens — into the prefix index,
        so the pages stay cached (refcount 1, index-held) instead of
        returning to the free list; everything else (partial tail
        page, failed/cancelled streams) frees as refcounts hit zero."""
        if self._slots[seq.slot] is not seq:
            return
        if event == "completed" and self.prefix is not None \
                and seq.published:
            # history[:ctx] are the positions whose K/V is actually in
            # the pool (the final emitted token was never written);
            # under speculation, cap at what the DRAFT pool also holds
            # so shared pages are valid in both pools
            n_ok = seq.ctx if self.draft is None \
                else min(seq.ctx, seq.draft_ctx)
            self.prefix.publish(seq.history, seq.pages, n_tokens=n_ok)
        self._slots[seq.slot] = None
        self._tables[seq.slot, :] = 0
        freed = self.kv.release(seq.pages)
        self.metrics.observe_evictions(freed)
        self.metrics.count(event)
        self.metrics.set_kv_pages(self.kv.used_pages,
                                  self.kv.free_pages)
        self._lock.notify_all()
