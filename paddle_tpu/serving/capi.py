"""C-ABI integration: route PD_* predictors through a shared server.

``native/csrc/pd_capi.cc`` calls ``wrap_capi(pred)`` after every
``PD_PredictorCreate``. With ``FLAGS_serving_capi_batching`` off
(default) the predictor passes through untouched — the existing
single-request capi behavior. With it on, all PD_Predictors created for
the same model prefix share ONE underlying Predictor + InferenceServer,
and each wrapper's ``run()`` submits to the shared queue and blocks on
its future — so a C host running the standard one-PD_Predictor-per-
thread pattern gets its threads' requests coalesced into device batches
with zero client-side changes.

Each wrapper keeps its OWN input/output handle Tensors (the C contract
scopes handles to a predictor), with output handles stable per fetch
name across runs (ADVICE #1 semantics).
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["wrap_capi"]

_lock = threading.Lock()
_shared: Dict[tuple, "InferenceServerEntry"] = {}


class InferenceServerEntry:
    __slots__ = ("server", "refs")

    def __init__(self, server):
        self.server = server
        self.refs = 0


def _server_for(pred):
    from .server import InferenceServer

    cfg = getattr(pred, "_config", None)
    key = (getattr(cfg, "_prefix", None) or id(pred),
           getattr(cfg, "_params_path", None))
    with _lock:
        entry = _shared.get(key)
        if entry is None:
            entry = _shared[key] = InferenceServerEntry(InferenceServer(
                pred, name=f"capi_{len(_shared)}"))
        entry.refs += 1
        return entry.server


class CapiServingPredictor:
    """Predictor-shaped facade over a shared InferenceServer — exposes
    exactly the surface pd_capi.cc touches."""

    def __init__(self, server):
        from ..inference import Tensor

        self._server = server
        base = server.predictor
        self._inputs = {
            name: Tensor(name, spec)
            for name, spec in zip(base._artifact.feed_names,
                                  base._artifact.feeds)}
        self._outputs: Dict[str, object] = {}
        self._Tensor = Tensor

    def get_input_names(self):
        return list(self._server.predictor.get_input_names())

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return sorted(self._outputs) or ["fetch_0"]

    def get_output_handle(self, name):
        t = self._outputs.get(name)
        if t is None:
            t = self._outputs[name] = self._Tensor(name)
        return t

    def run(self):
        feeds = []
        for n in self._server.predictor.get_input_names():
            h = self._inputs[n]
            if h._value is None:
                raise RuntimeError(f"input '{n}' not set")
            feeds.append(h._value)
        fut = self._server.submit(feeds)
        outs = fut.result()
        for i, o in enumerate(outs):
            self.get_output_handle(f"fetch_{i}")._value = o
        return True

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def wrap_capi(pred):
    """Identity unless FLAGS_serving_capi_batching is enabled (called
    from pd_capi.cc; must never raise — a serving-layer problem should
    degrade to the plain predictor, not kill PD_PredictorCreate)."""
    try:
        from ..framework.flags import flag_value
        if not flag_value("FLAGS_serving_capi_batching"):
            return pred
        return CapiServingPredictor(_server_for(pred))
    except Exception:  # noqa: BLE001 - degrade, never break the C ABI
        return pred
