"""InferenceServer: request-level dynamic-batching serving loop.

Owns a ``paddle_tpu.inference.Predictor`` and turns its one-shot
``run`` into a request-level service: callers ``submit`` per-request
feeds and get a Future; a worker thread drains the bounded queue and
coalesces shape-compatible requests into one padded device batch
(bucketing.py). Execution is a 3-stage pipeline:

1. **host assembly** (worker thread): requests are copied into a
   persistent staging-buffer pool keyed by ``(signature, padded_rows)``
   — no fresh ``np.zeros``/``np.concatenate`` per batch;
2. **device stage** (worker thread): ``device_put`` + dispatch through
   the Predictor's async ``dispatch_many`` (donated input buffers on
   backends that support donation). JAX async dispatch means the call
   returns before compute finishes;
3. **completion** (dedicated thread): blocks on the device result,
   fetches, unpads, and resolves each request's Future.

The worker hands dispatched batches to the completion thread over a
bounded queue (``FLAGS_serving_pipeline_depth`` deep), so batch N+1's
host assembly overlaps batch N's device compute while backpressure,
per-request deadlines, and the fault barrier still hold. The queue is
FIFO and drained serially, so request→response ordering is unchanged
from the synchronous executor (``pipeline_depth=0`` restores it).

Why a layer above Predictor instead of a faster ``run``: VERDICT.md
measured single-request serving as host-dominated (ERNIE-base p50 ~21x
device compute) — the win is amortizing that host overhead over many
requests per device dispatch and overlapping what host work remains
with device compute, which needs a queue + pipeline, not a faster call.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..observability import tracing
from . import metrics as metrics_mod
from .batcher import DynamicBatcher
from .bucketing import BucketSpec, ShapeBucketPolicy
from .request import (DeadlineExceededError, QueueFullError, Request,
                      ServerClosedError)

__all__ = ["InferenceServer"]

FeedLike = Union[Dict[str, np.ndarray], Sequence[np.ndarray]]


def _flag(name, default):
    from ..framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class _StagingPool:
    """Persistent host staging buffers, a ring per
    ``(signature, padded_rows)`` key.

    Assembly writes each batch into the next ring slot instead of
    allocating fresh arrays; the ring holds ``pipeline_depth + 2``
    slots so a slot is never rewritten while any batch that used it can
    still be un-fetched (at most ``depth`` batches sit in the hand-off
    queue plus one inside the completion thread — +2 covers the one
    being assembled). That also keeps the pool safe if ``device_put``
    zero-copy-aliases an aligned host buffer (the CPU PJRT client
    does)."""

    def __init__(self, slots: int):
        self._slots = max(2, int(slots))
        self._rings: Dict[Tuple, Tuple[list, list]] = {}

    def __len__(self):
        return len(self._rings)

    def acquire(self, key: Tuple, feed_shapes) -> List[np.ndarray]:
        """Next buffer set for ``key``; ``feed_shapes`` is
        ``[(shape, dtype), ...]`` used only on first allocation."""
        ring = self._rings.get(key)
        if ring is None:
            bufs = [[np.zeros(s, d) for s, d in feed_shapes]
                    for _ in range(self._slots)]
            ring = self._rings[key] = (bufs, [0])
        bufs, idx = ring
        out = bufs[idx[0]]
        idx[0] = (idx[0] + 1) % self._slots
        return out


class _Inflight:
    """A dispatched-but-unfetched batch riding the completion queue.
    ``traced`` holds the batch's trace-carrying requests (usually
    empty) so the completion stage knows to emit device_wait/fetch
    spans into their traces."""

    __slots__ = ("batch", "pending", "rows", "padded_rows",
                 "assembly_ms", "dispatch_ms", "record_latency",
                 "record_traffic", "traced")

    def __init__(self, batch, pending, rows, padded_rows, assembly_ms,
                 dispatch_ms, record_latency, record_traffic,
                 traced=()):
        self.batch = batch
        self.pending = pending
        self.rows = rows
        self.padded_rows = padded_rows
        self.assembly_ms = assembly_ms
        self.dispatch_ms = dispatch_ms
        self.record_latency = record_latency
        self.record_traffic = record_traffic
        self.traced = traced


class InferenceServer:
    """Dynamic-batching server over one Predictor.

    Parameters default to the ``FLAGS_serving_*`` knobs
    (framework/flags.py) so a deployment can be tuned without code
    changes. ``seq_buckets``/``seq_axis`` opt into sequence-length
    bucketing (see ShapeBucketPolicy for the independence assumption);
    batch-row padding to powers of two is on by default and can be
    disabled with ``pad_batch=False``. ``pipeline_depth`` bounds how
    many dispatched batches may await completion (0 = synchronous
    execute); ``donate_inputs`` donates device input buffers to the
    jitted dispatch on backends with donation support.

    ``start=False`` defers the worker thread: requests queue up until
    ``start()`` (or ``serve_forever``) — useful for tests and for
    pre-loading a queue before measuring.
    """

    def __init__(self, predictor, *, max_batch_size: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 pad_batch: Optional[bool] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1, name: str = "default",
                 pipeline_depth: Optional[int] = None,
                 donate_inputs: Optional[bool] = None,
                 telemetry_port: Optional[int] = None,
                 ready_requires_warmup: Optional[bool] = None,
                 scheduler=None, start: bool = True):
        self.predictor = predictor
        self.max_batch_size = int(max_batch_size if max_batch_size
                                  is not None
                                  else _flag("FLAGS_serving_max_batch_size",
                                             8))
        self.max_wait_ms = float(max_wait_ms if max_wait_ms is not None
                                 else _flag("FLAGS_serving_max_wait_ms",
                                            2.0))
        cap = queue_capacity if queue_capacity is not None \
            else _flag("FLAGS_serving_queue_capacity", 64)
        self.default_timeout_ms = default_timeout_ms \
            if default_timeout_ms is not None \
            else (_flag("FLAGS_serving_default_timeout_ms", 0.0) or None)
        if pad_batch is None:
            pad_batch = bool(_flag("FLAGS_serving_pad_batch_pow2", True))
        self.pipeline_depth = max(0, int(
            pipeline_depth if pipeline_depth is not None
            else _flag("FLAGS_serving_pipeline_depth", 2)))
        self._donate = bool(donate_inputs if donate_inputs is not None
                            else _flag("FLAGS_serving_donate_inputs", True))
        self.policy = ShapeBucketPolicy(
            max_batch_size=self.max_batch_size, pad_batch=pad_batch,
            seq_buckets=seq_buckets, seq_axis=seq_axis)
        self.metrics = metrics_mod.register(metrics_mod.ServingMetrics(
            name, window=int(_flag("FLAGS_serving_latency_window", 2048))))
        self.scheduler = scheduler  # scheduling.AdmissionController
        if scheduler is not None:
            from .scheduling.schedz import register_controller
            register_controller(scheduler)
        self._batcher = DynamicBatcher(
            max_batch_size=self.max_batch_size,
            max_wait_ms=self.max_wait_ms, capacity=int(cap),
            metrics=self.metrics, scheduler=scheduler)
        self._feed_names = list(predictor.get_input_names())
        self._staging = _StagingPool(self.pipeline_depth + 2)
        self._completion_q: "queue.Queue[Optional[_Inflight]]" = \
            queue.Queue(maxsize=max(1, self.pipeline_depth))
        self._completion_thread: Optional[threading.Thread] = None
        self._closed = False
        self._worker: Optional[threading.Thread] = None
        self._loop_running = False      # a thread is inside _loop
        self._compiled = set()          # signatures already executed
        self._manifest_recorded = set()  # signatures already persisted
        self._lock = threading.Lock()
        # readiness (distinct from liveness): with the gate on, the
        # server reports not-ready until a warmup pass completes, so a
        # fleet router never routes traffic to a cold replica that
        # would compile on the request path
        self._ready_gate = bool(
            ready_requires_warmup if ready_requires_warmup is not None
            else _flag("FLAGS_serving_ready_requires_warmup", False))
        self._warmed = threading.Event()
        self.telemetry = self._attach_telemetry(telemetry_port)
        self._manifest = self._init_manifest()
        if self._manifest is not None and len(self._manifest) and \
                bool(_flag("FLAGS_serving_warmup_from_manifest", False)):
            # restart fast path: pre-compile exactly the signatures the
            # previous process served — each one a persistent-cache
            # load when the compile cache is warm
            self.warmup_from_manifest()
        if start:
            self.start()

    def _attach_telemetry(self, telemetry_port: Optional[int]):
        """Attach the shared observability endpoint (/metrics /healthz
        /statusz). Port -1 = off (the flag default), 0 = ephemeral,
        >0 = fixed. The endpoint is process-wide and outlives this
        server — the registry it exposes aggregates every subsystem —
        so shutdown() deregisters only this server's health check."""
        port = telemetry_port if telemetry_port is not None \
            else _flag("FLAGS_serving_telemetry_port", -1)
        if port is None or int(port) < 0:
            return None
        from .. import observability
        srv = observability.start_telemetry_server(port=int(port))
        observability.add_health_check(
            f"serving:{self.metrics.name}", self._health)
        observability.add_readiness_check(
            f"serving:{self.metrics.name}", self._readiness)
        return srv

    def _init_manifest(self):
        """Warmup manifest for this (server, model) pair under the
        persistent compile-cache directory; None when the cache is
        disabled or the predictor has no stable artifact identity (the
        protobuf-program path)."""
        if not str(_flag("FLAGS_compile_cache_dir", "") or ""):
            return None
        try:
            from ..compile_cache import WarmupManifest, default_cache
            cache = default_cache()
            fp_fn = getattr(self.predictor, "artifact_fingerprint", None)
            fp = fp_fn() if callable(fp_fn) else None
            if cache is None or fp is None:
                return None
            return WarmupManifest(WarmupManifest.default_path(
                cache.directory, self.metrics.name, fp))
        except Exception:  # noqa: BLE001 - the manifest is an
            return None    # optimization artifact, never a hard dep

    @property
    def warmup_manifest(self):
        """The live WarmupManifest (or None): runtime-dispatched batch
        signatures, written through to disk as they first appear."""
        return self._manifest

    def _health(self):
        """Healthy while accepting traffic: not shut down, and if the
        worker was ever started it must still be alive."""
        if self._closed:
            return False, "shut down"
        w = self._worker
        if w is not None and not w.is_alive() and not self._loop_running:
            return False, "worker thread died"
        return True, {"queue_depth": self.queue_depth,
                      "inflight_batches": self.inflight_batches}

    # ------------------------------------------------------ readiness
    @property
    def ready(self) -> bool:
        """True when this server should be handed traffic. Without the
        warmup gate (``FLAGS_serving_ready_requires_warmup`` /
        ``ready_requires_warmup=``) any live server is ready; with it,
        readiness additionally requires a completed ``warmup()`` /
        ``warmup_from_manifest()`` (or explicit ``mark_ready()``)."""
        if self._closed:
            return False
        return self._warmed.is_set() or not self._ready_gate

    def mark_ready(self):
        """Flip readiness on without a warmup pass (a deployment that
        accepts compiling on the request path)."""
        self._warmed.set()

    def _readiness(self):
        ok = self.ready
        return ok, {"warmed": self._warmed.is_set(),
                    "gated": self._ready_gate}

    # ------------------------------------------------------ lifecycle
    def start(self):
        with self._lock:
            if self._closed:
                raise ServerClosedError("server already shut down")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._loop, name=f"serving-{self.metrics.name}",
                    daemon=True)
                self._worker.start()
        return self

    def serve_forever(self):
        """Run the batching loop in the CALLING thread until
        ``shutdown`` (from another thread) — the synchronous deployment
        mode, mirroring the reference C++ serving hosts that own the
        loop themselves. (The completion stage still runs on its own
        thread when ``pipeline_depth > 0``.)"""
        with self._lock:
            if self._closed:
                raise ServerClosedError("server already shut down")
            if self._worker is not None and self._worker.is_alive():
                raise RuntimeError(
                    "worker thread already running; serve_forever is the "
                    "no-thread mode (construct with start=False)")
        self._loop()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests; with ``drain`` (default) finish
        everything already queued AND in flight in the pipeline,
        otherwise fail still-queued futures with ServerClosedError
        (already-dispatched batches complete either way). Idempotent."""
        with self._lock:
            self._closed = True
        if not drain:
            self._batcher.cancel_pending(
                ServerClosedError("server shut down before this request "
                                  "was scheduled"))
        self._batcher.stop()      # worker exits once the queue is empty
        w = self._worker
        if w is not None and w.is_alive() and \
                w is not threading.current_thread():
            w.join(timeout)
        elif drain and not self._loop_running:
            # never-started server (start=False): drain inline so
            # queued futures still resolve; a live serve_forever loop
            # drains itself (stop() above lets it exit once empty)
            self._loop()
        else:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while drain and self._loop_running and \
                    (deadline is None or time.monotonic() < deadline):
                time.sleep(0.005)  # wait out a serve_forever drain
        self._stop_completion(timeout)
        if self.telemetry is not None:
            from ..observability import (remove_health_check,
                                         remove_readiness_check)
            remove_health_check(f"serving:{self.metrics.name}")
            remove_readiness_check(f"serving:{self.metrics.name}")
        metrics_mod.unregister(self.metrics.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
        return False

    # ------------------------------------------------------ submission
    def _normalize(self, feed: FeedLike) -> List[np.ndarray]:
        if isinstance(feed, dict):
            missing = [n for n in self._feed_names if n not in feed]
            if missing:
                raise KeyError(f"feed missing inputs {missing}")
            arrs = [np.asarray(feed[n]) for n in self._feed_names]
        else:
            arrs = [a if type(a) is np.ndarray else np.asarray(a)
                    for a in feed]
            if len(arrs) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} feeds "
                    f"({self._feed_names}), got {len(arrs)}")
        return arrs

    def submit(self, feed: FeedLike,
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None):
        """Enqueue one request; returns a Future resolving to the list
        of output arrays for THIS request (padded rows/positions already
        sliced away). Raises QueueFullError at capacity (or its
        QuotaExceededError subclass when a ``scheduler`` sheds the
        ``tenant``) and ServerClosedError after shutdown."""
        if self._closed:
            raise ServerClosedError("server is shut down")
        req = self._make_request(feed, timeout_ms,
                                 trace=tracing.request_context(),
                                 tenant=tenant)
        self.metrics.count("submitted")
        try:
            self._batcher.put(req)
        except QueueFullError:
            self.metrics.count("rejected")
            self._trace_shed([req])
            raise
        return req.future

    def _trace_shed(self, reqs: Sequence[Request]):
        """Tail-promote shed requests: a QueueFullError is exactly the
        kind of tail event an unsampled trace must still record."""
        now = time.time_ns()
        for r in reqs:
            if r.trace is not None:
                tracing.record_span(
                    r.trace, "serving::shed", stage="shed",
                    start_unix_ns=now, duration_ms=0.0,
                    status="error",
                    attrs={"server": self.metrics.name,
                           "error": "QueueFullError"}, root=True)

    def _make_request(self, feed: FeedLike,
                      timeout_ms: Optional[float],
                      trace=None,
                      tenant: Optional[str] = None) -> Request:
        arrs = self._normalize(feed)
        rows = int(arrs[0].shape[0]) if arrs[0].ndim else 1
        if rows > self.max_batch_size:
            raise ValueError(
                f"request carries {rows} rows > max_batch_size="
                f"{self.max_batch_size}; split it or raise the cap")
        orig_seq = None
        if self.policy.seq_buckets is not None:
            ax = self.policy.seq_axis
            orig_seq = [int(a.shape[ax]) if a.ndim > ax else -1
                        for a in arrs]
            arrs = self.policy.pad_request_seq(arrs)
        # the request's trace context gets a child identity: that
        # child's span id IS the serving::request span, and the stage
        # spans (queue/assembly/...) parent under it
        return Request(arrs, rows, self.policy.signature(arrs),
                       orig_seq=orig_seq,
                       timeout_ms=timeout_ms if timeout_ms is not None
                       else self.default_timeout_ms,
                       trace=trace.child() if trace is not None
                       else None,
                       tenant=tenant)

    def submit_many(self, feeds: Sequence[FeedLike],
                    timeout_ms: Optional[float] = None,
                    trace_contexts: Optional[Sequence] = None):
        """Bulk ``submit``: requests are validated up front and
        enqueued with ONE batcher lock acquisition / metrics update —
        the per-request lock+notify+stat cost of a submit loop is real
        at high ingest rates. All-or-nothing on capacity: raises
        QueueFullError without enqueueing any of the batch.
        ``trace_contexts`` (one per feed, None entries allowed) carries
        propagated trace identities — the fleet worker's path; without
        it each request picks up the ambient/sampled context like
        ``submit``."""
        if self._closed:
            raise ServerClosedError("server is shut down")
        if trace_contexts is None:
            reqs = [self._make_request(f, timeout_ms,
                                       trace=tracing.request_context())
                    for f in feeds]
        else:
            reqs = [self._make_request(f, timeout_ms, trace=ctx)
                    for f, ctx in zip(feeds, trace_contexts)]
        self.metrics.count("submitted", len(reqs))
        try:
            self._batcher.put_many(reqs)
        except QueueFullError:
            self.metrics.count("rejected", len(reqs))
            self._trace_shed(reqs)
            raise
        return [r.future for r in reqs]

    # ------------------------------------------------------- warmup
    def bucket_specs(self) -> List[BucketSpec]:
        """The full bucket lattice traffic can land on: power-of-two
        batch buckets up to max_batch_size crossed with the configured
        seq buckets. ``warmup(server.bucket_specs())`` pre-compiles
        everything, so steady state runs compile-free. (With
        ``pad_batch=False`` every row count is its own shape; only the
        max-batch point is returned.)"""
        if self.policy.pad_batch:
            batches, b = [], 1
            while b < self.max_batch_size:
                batches.append(b)
                b <<= 1
            batches.append(self.max_batch_size)
        else:
            batches = [self.max_batch_size]
        seqs = self.policy.seq_buckets or [None]
        return [BucketSpec(b, s) for b in batches for s in seqs]

    def warmup(self, bucket_specs: Optional[Sequence] = None) -> int:
        """Pre-compile the bucket lattice: for each spec — a BucketSpec,
        an int batch bucket, or a (batch, seq) tuple — run one zero
        batch through the predictor so XLA compiles it before traffic
        arrives; defaults to the full ``bucket_specs()`` lattice.
        Returns the number of fresh compiles triggered. Warmup batches
        hit the compile-cache metric but NOT the traffic metrics
        (completed count, batch/padding histograms, latency, stage
        times), so steady-state dashboards aren't skewed by them."""
        if bucket_specs is None:
            bucket_specs = self.bucket_specs()
        specs = []
        for s in bucket_specs:
            if isinstance(s, BucketSpec):
                specs.append(s)
            elif isinstance(s, (tuple, list)):
                specs.append(BucketSpec(*s))
            else:
                specs.append(BucketSpec(int(s)))
        feed_specs = getattr(self.predictor, "_artifact").feeds
        fresh = 0
        for spec in specs:
            arrs = []
            for fs in feed_specs:
                shape = [d if d not in (None, -1) else 1
                         for d in fs["shape"]]
                shape[0] = spec.batch
                ax = self.policy.seq_axis
                if spec.seq is not None and len(shape) > ax:
                    shape[ax] = spec.seq
                arrs.append(np.zeros(tuple(shape), fs["dtype"]))
            sig = self.policy.signature(arrs)
            req = Request(arrs, spec.batch, sig)
            fresh += self._execute([req], record_latency=False,
                                   record_traffic=False)
            req.future.result()    # surface warmup failures loudly
        self._warmed.set()
        return fresh

    def warmup_from_manifest(self, path: Optional[str] = None) -> int:
        """Replay the persisted warmup manifest: pre-compile exactly the
        padded batch signatures a previous process dispatched (each one
        a persistent-cache load when ``FLAGS_compile_cache_dir`` is
        warm) instead of the full theoretical lattice. Returns the
        fresh-compile count like ``warmup``; 0 when no manifest exists.
        Signatures recorded under a larger ``max_batch_size`` than this
        server's are skipped — they cannot occur here."""
        if path is not None:
            from ..compile_cache import WarmupManifest
            manifest = WarmupManifest(path)
        else:
            manifest = self._manifest
        if manifest is None:
            return 0
        fresh = 0
        # only the batch-predict signatures: decode-engine entries
        # ("generate_*" sites) are replayed by GenerationServer, whose
        # feeds mean nothing to the Predictor dispatch
        for spec in manifest.specs(site="predict"):
            arrs = [np.zeros(tuple(shape), dtype)
                    for shape, dtype in spec["feeds"]]
            rows = int(arrs[0].shape[0]) if arrs[0].ndim else 1
            if rows > self.max_batch_size:
                continue
            req = Request(arrs, rows, self.policy.signature(arrs))
            fresh += self._execute([req], record_latency=False,
                                   record_traffic=False)
            req.future.result()    # surface replay failures loudly
        self._warmed.set()
        return fresh

    # ------------------------------------------------------ execution
    def _loop(self):
        with self._lock:
            self._loop_running = True
        pipelined = self.pipeline_depth > 0
        try:
            while True:
                batch = self._batcher.next_batch()
                if batch is None:
                    return
                if pipelined:
                    inflight, _ = self._dispatch(batch)
                    if inflight is not None:
                        self._ensure_completion_thread()
                        # bounded hand-off: blocks at pipeline_depth
                        # outstanding batches (backpressure propagates
                        # to the request queue, then QueueFullError)
                        self._completion_q.put(inflight)
                else:
                    self._execute(batch)
        finally:
            if pipelined:
                self._drain_pipeline()
            with self._lock:
                self._loop_running = False

    # ---- stage 1: host assembly (staging pool) ----
    def _assemble(self, batch: List[Request], sig, padded_rows: int
                  ) -> List[np.ndarray]:
        """Copy the batch's feeds into the persistent staging buffers
        for ``(sig, padded_rows)``, zeroing the pad rows — replaces a
        per-batch np.concatenate plus fresh np.zeros pad blocks."""
        feed_shapes = [((padded_rows,) + tuple(shape), dtype)
                       for dtype, shape in sig]
        bufs = self._staging.acquire((sig, padded_rows), feed_shapes)
        for i, buf in enumerate(bufs):
            ofs = 0
            for r in batch:
                buf[ofs:ofs + r.rows] = r.feeds[i]
                ofs += r.rows
            if ofs < padded_rows:
                buf[ofs:] = 0
        return bufs

    # ---- stage 2: transfer + async device dispatch ----
    def _dispatch(self, batch: List[Request], record_latency: bool = True,
                  record_traffic: bool = True):
        """Assemble + dispatch one coalesced batch WITHOUT waiting for
        results. Returns ``(inflight, miss)`` — inflight is None when
        dispatch itself failed (futures already resolved with the
        error; the fault barrier keeps the worker alive)."""
        from ..profiler import RecordEvent

        rows = sum(r.rows for r in batch)
        padded_rows = self.policy.bucket_batch(rows)
        sig = batch[0].signature
        if record_traffic:
            # padding waste: real input elements vs elements the padded
            # device batch actually carries
            per_row = self.policy.elements_per_row(sig)
            real = sum(int(np.prod(a.shape)) if a.ndim else 1
                       for r in batch for a in r.feeds)
            self.metrics.observe_batch(rows, real, padded_rows * per_row)

        cache_key = (sig, padded_rows)
        miss = cache_key not in self._compiled
        self._compiled.add(cache_key)
        # counted on EVERY dispatch (runtime included), not just during
        # warmup — steady-state traffic shows up as a stream of hits,
        # so a dashboard can tell "compile-free" from "no data"
        self.metrics.observe_compile(hit=not miss, signature=cache_key)
        if record_traffic and self._manifest is not None and \
                cache_key not in self._manifest_recorded:
            # first TRAFFIC dispatch of this signature (whether or not
            # warmup pre-compiled it): persist it so a restarted server
            # pre-warms exactly the lattice real traffic lands on
            self._manifest_recorded.add(cache_key)
            self._manifest.record(
                [((padded_rows,) + tuple(shape), str(np.dtype(dtype)))
                 for dtype, shape in sig])

        rows_list = [r.rows for r in batch]
        n_pad = padded_rows - rows
        if n_pad:
            # the pad block rides as a trailing pseudo-request so
            # fetch_many's slices line up; its outputs are discarded
            rows_list.append(n_pad)
        span_args = {"rows": rows, "padded": padded_rows}
        # request tracing: warmup batches (record_traffic=False) carry
        # no trace contexts by construction, so the flight recorder
        # only ever sees real traffic
        traced = [r for r in batch if r.trace is not None] \
            if record_traffic else []
        t_wall = time.time_ns() if traced else 0
        t0 = time.perf_counter()
        try:
            with RecordEvent("serving::assemble", args=span_args):
                assembled = self._assemble(batch, sig, padded_rows)
            t1 = time.perf_counter()
            with RecordEvent("serving::dispatch", args=span_args):
                pending = self.predictor.dispatch_many(
                    assembled=assembled, rows=rows_list,
                    donate=self._donate)
        except Exception as e:  # noqa: BLE001 - fault barrier: the
            # worker thread must survive any model error and fail only
            # the requests of THIS batch
            for r in batch:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                self.metrics.count("failed")
            self._trace_failed(traced, "dispatch", e)
            return None, int(miss)
        t2 = time.perf_counter()
        assembly_ms = (t1 - t0) * 1e3
        dispatch_ms = (t2 - t1) * 1e3
        for r in traced:
            # queue wait = submit to batch formation; the stage spans
            # reuse the batch's measured intervals anchored on the
            # wall clock so cross-process stitching lines up
            attrs = dict(span_args, server=self.metrics.name)
            tracing.record_span(
                r.trace, "serving::queue", stage="queue",
                start_unix_ns=r.t_wall_ns,
                duration_ms=max(0.0, (t_wall - r.t_wall_ns) / 1e6),
                attrs=attrs)
            tracing.record_span(
                r.trace, "serving::assembly", stage="assembly",
                start_unix_ns=t_wall, duration_ms=assembly_ms,
                attrs=attrs)
            tracing.record_span(
                r.trace, "serving::dispatch", stage="dispatch",
                start_unix_ns=t_wall + int(assembly_ms * 1e6),
                duration_ms=dispatch_ms,
                attrs=dict(attrs, compile_miss=bool(miss)))
        return _Inflight(batch, pending, rows, padded_rows,
                         assembly_ms, dispatch_ms,
                         record_latency, record_traffic,
                         traced=traced), int(miss)

    def _trace_failed(self, traced, stage: str, exc: BaseException):
        """Error spans + tail promotion for a failed batch's traced
        requests (the fault-barrier counterpart of the happy-path
        stage spans)."""
        now = time.time_ns()
        for r in traced:
            tracing.record_span(
                r.trace, f"serving::{stage}", stage=stage,
                start_unix_ns=now, duration_ms=0.0, status="error",
                attrs={"server": self.metrics.name,
                       "error": f"{type(exc).__name__}: {exc}"},
                root=True)

    # ---- stage 3: completion (block, fetch, unpad, resolve) ----
    def _complete(self, inf: _Inflight):
        from ..profiler import RecordEvent

        batch = inf.batch
        span = RecordEvent("serving::complete",
                           args={"rows": inf.rows,
                                 "padded": inf.padded_rows})
        t_wall = time.time_ns() if inf.traced else 0
        try:
            with span:
                t0 = time.perf_counter()
                inf.pending.block()          # device compute-wait
                t1 = time.perf_counter()
                results = self.predictor.fetch_many(inf.pending)
                t2 = time.perf_counter()
                span.set_arg("device_wait_ms",
                             round((t1 - t0) * 1e3, 3))
                span.set_arg("fetch_ms", round((t2 - t1) * 1e3, 3))
        except Exception as e:  # noqa: BLE001 - fault barrier: a fetch
            # error fails THIS batch only; the completion thread and
            # any other in-flight batch keep going
            for r in batch:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                self.metrics.count("failed")
            self._trace_failed(inf.traced, "fetch", e)
            return
        for r in inf.traced:
            attrs = {"rows": inf.rows, "padded": inf.padded_rows,
                     "server": self.metrics.name}
            tracing.record_span(
                r.trace, "serving::device_wait", stage="device_wait",
                start_unix_ns=t_wall, duration_ms=(t1 - t0) * 1e3,
                attrs=attrs)
            tracing.record_span(
                r.trace, "serving::fetch", stage="fetch",
                start_unix_ns=t_wall + int((t1 - t0) * 1e9),
                duration_ms=(t2 - t1) * 1e3, attrs=attrs)
        completed = 0
        latencies = []
        for r, outs in zip(batch, results):   # padding slice (if any)
            if not r.future.set_running_or_notify_cancel():
                continue                      # cancelled between drain+run
            if r.orig_seq is not None and r.orig_seq[0] > 0:
                # outputs are unpadded against the FIRST feed's original
                # sequence length (the single-sequence-input common case)
                outs = [self.policy.unpad_output(o, r.orig_seq[0])
                        for o in outs]
            r.future.set_result(outs)
            completed += 1
            if inf.record_latency:
                latencies.append(r.latency_ms())
            if r.trace is not None:
                lat = r.latency_ms()
                tracing.record_span(
                    r.trace, "serving::request", stage="request",
                    start_unix_ns=r.t_wall_ns, duration_ms=lat,
                    attrs={"rows": r.rows,
                           "server": self.metrics.name}, root=True)
                tracing.record_exemplar("paddle_serving_latency_ms",
                                        lat, r.trace.trace_id)
        # metrics are bulked per BATCH, not per request: count/stat_add
        # take two locks each, a measurable tax at high request rates
        if inf.record_traffic and completed:
            self.metrics.count("completed", completed)
        if latencies:
            self.metrics.observe_latency_many(latencies)
        if inf.record_traffic:
            self.metrics.observe_stage_times(
                inf.assembly_ms, inf.dispatch_ms,
                (t1 - t0) * 1e3, (t2 - t1) * 1e3)

    def _execute(self, batch: List[Request], record_latency: bool = True,
                 record_traffic: bool = True) -> int:
        """Synchronous path (warmup and ``pipeline_depth=0``): dispatch
        then complete inline. Returns 1 on a compile-cache miss (a
        shape XLA had not seen), else 0."""
        inflight, miss = self._dispatch(batch, record_latency,
                                        record_traffic)
        if inflight is not None:
            self._complete(inflight)
        return miss

    # ---- completion thread plumbing ----
    def _ensure_completion_thread(self):
        t = self._completion_thread
        if t is None or not t.is_alive():
            self._completion_thread = t = threading.Thread(
                target=self._completion_loop,
                name=f"serving-complete-{self.metrics.name}", daemon=True)
            t.start()

    def _completion_loop(self):
        while True:
            inf = self._completion_q.get()
            try:
                if inf is None:          # shutdown sentinel
                    return
                self._complete(inf)      # has its own fault barrier
            except Exception as e:  # noqa: BLE001 - belt and braces:
                # even a bug past _complete's barrier (unpad, metrics)
                # must not kill the completion thread mid-traffic
                for r in inf.batch:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
            finally:
                self._completion_q.task_done()

    def _drain_pipeline(self, timeout: Optional[float] = None):
        """Wait until every dispatched batch has completed (or the
        completion thread died / ``timeout`` elapsed)."""
        q = self._completion_q
        deadline = None if timeout is None else time.monotonic() + timeout
        with q.all_tasks_done:
            while q.unfinished_tasks:
                t = self._completion_thread
                if t is None or not t.is_alive():
                    return
                if deadline is not None and time.monotonic() > deadline:
                    return
                q.all_tasks_done.wait(0.05)

    def _stop_completion(self, timeout: Optional[float] = None):
        t = self._completion_thread
        if t is not None and t.is_alive():
            self._completion_q.put(None)
            t.join(timeout)
        self._completion_thread = None

    # ------------------------------------------------------ inspection
    @property
    def queue_depth(self) -> int:
        return len(self._batcher)

    @property
    def inflight_batches(self) -> int:
        """Dispatched batches not yet completed (pipeline occupancy)."""
        return self._completion_q.unfinished_tasks

    def metrics_json(self, indent: Optional[int] = None) -> str:
        return self.metrics.to_json(indent=indent)
