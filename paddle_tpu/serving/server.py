"""InferenceServer: request-level dynamic-batching serving loop.

Owns a ``paddle_tpu.inference.Predictor`` and turns its one-shot
``run`` into a request-level service: callers ``submit`` per-request
feeds and get a Future; a worker thread drains the bounded queue,
coalesces shape-compatible requests into one padded device batch
(bucketing.py), executes through the Predictor's batched ``run_many``
fast path, and resolves each Future with that request's unpadded
outputs. ``warmup`` pre-compiles the bucket lattice so steady-state
traffic never hits an XLA compile.

Why a layer above Predictor instead of a faster ``run``: VERDICT.md
measured single-request serving as host-dominated (ERNIE-base p50 ~21x
device compute) — the win is amortizing that host overhead over many
requests per device dispatch, which needs a queue, not a faster call.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from . import metrics as metrics_mod
from .batcher import DynamicBatcher
from .bucketing import BucketSpec, ShapeBucketPolicy
from .request import (DeadlineExceededError, QueueFullError, Request,
                      ServerClosedError)

__all__ = ["InferenceServer"]

FeedLike = Union[Dict[str, np.ndarray], Sequence[np.ndarray]]


def _flag(name, default):
    from ..framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class InferenceServer:
    """Dynamic-batching server over one Predictor.

    Parameters default to the ``FLAGS_serving_*`` knobs
    (framework/flags.py) so a deployment can be tuned without code
    changes. ``seq_buckets``/``seq_axis`` opt into sequence-length
    bucketing (see ShapeBucketPolicy for the independence assumption);
    batch-row padding to powers of two is on by default and can be
    disabled with ``pad_batch=False``.

    ``start=False`` defers the worker thread: requests queue up until
    ``start()`` (or ``serve_forever``) — useful for tests and for
    pre-loading a queue before measuring.
    """

    def __init__(self, predictor, *, max_batch_size: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 pad_batch: Optional[bool] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1, name: str = "default",
                 start: bool = True):
        self.predictor = predictor
        self.max_batch_size = int(max_batch_size if max_batch_size
                                  is not None
                                  else _flag("FLAGS_serving_max_batch_size",
                                             8))
        self.max_wait_ms = float(max_wait_ms if max_wait_ms is not None
                                 else _flag("FLAGS_serving_max_wait_ms",
                                            2.0))
        cap = queue_capacity if queue_capacity is not None \
            else _flag("FLAGS_serving_queue_capacity", 64)
        self.default_timeout_ms = default_timeout_ms \
            if default_timeout_ms is not None \
            else (_flag("FLAGS_serving_default_timeout_ms", 0.0) or None)
        if pad_batch is None:
            pad_batch = bool(_flag("FLAGS_serving_pad_batch_pow2", True))
        self.policy = ShapeBucketPolicy(
            max_batch_size=self.max_batch_size, pad_batch=pad_batch,
            seq_buckets=seq_buckets, seq_axis=seq_axis)
        self.metrics = metrics_mod.register(metrics_mod.ServingMetrics(
            name, window=int(_flag("FLAGS_serving_latency_window", 2048))))
        self._batcher = DynamicBatcher(
            max_batch_size=self.max_batch_size,
            max_wait_ms=self.max_wait_ms, capacity=int(cap),
            metrics=self.metrics)
        self._feed_names = list(predictor.get_input_names())
        self._closed = False
        self._worker: Optional[threading.Thread] = None
        self._loop_running = False      # a thread is inside _loop
        self._compiled = set()          # signatures already executed
        self._lock = threading.Lock()
        if start:
            self.start()

    # ------------------------------------------------------ lifecycle
    def start(self):
        with self._lock:
            if self._closed:
                raise ServerClosedError("server already shut down")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._loop, name=f"serving-{self.metrics.name}",
                    daemon=True)
                self._worker.start()
        return self

    def serve_forever(self):
        """Run the batching loop in the CALLING thread until
        ``shutdown`` (from another thread) — the synchronous deployment
        mode, mirroring the reference C++ serving hosts that own the
        loop themselves."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("server already shut down")
            if self._worker is not None and self._worker.is_alive():
                raise RuntimeError(
                    "worker thread already running; serve_forever is the "
                    "no-thread mode (construct with start=False)")
        self._loop()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests; with ``drain`` (default) finish
        everything already queued, otherwise fail pending futures with
        ServerClosedError. Idempotent."""
        with self._lock:
            self._closed = True
        if not drain:
            self._batcher.cancel_pending(
                ServerClosedError("server shut down before this request "
                                  "was scheduled"))
        self._batcher.stop()      # worker exits once the queue is empty
        w = self._worker
        if w is not None and w.is_alive() and \
                w is not threading.current_thread():
            w.join(timeout)
        elif drain and not self._loop_running:
            # never-started server (start=False): drain inline so
            # queued futures still resolve; a live serve_forever loop
            # drains itself (stop() above lets it exit once empty)
            self._loop()
        else:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while drain and self._loop_running and \
                    (deadline is None or time.monotonic() < deadline):
                time.sleep(0.005)  # wait out a serve_forever drain
        metrics_mod.unregister(self.metrics.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
        return False

    # ------------------------------------------------------ submission
    def _normalize(self, feed: FeedLike) -> List[np.ndarray]:
        if isinstance(feed, dict):
            missing = [n for n in self._feed_names if n not in feed]
            if missing:
                raise KeyError(f"feed missing inputs {missing}")
            arrs = [np.asarray(feed[n]) for n in self._feed_names]
        else:
            arrs = [np.asarray(a) for a in feed]
            if len(arrs) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} feeds "
                    f"({self._feed_names}), got {len(arrs)}")
        return arrs

    def submit(self, feed: FeedLike,
               timeout_ms: Optional[float] = None):
        """Enqueue one request; returns a Future resolving to the list
        of output arrays for THIS request (padded rows/positions already
        sliced away). Raises QueueFullError at capacity and
        ServerClosedError after shutdown."""
        if self._closed:
            raise ServerClosedError("server is shut down")
        arrs = self._normalize(feed)
        rows = int(arrs[0].shape[0]) if arrs[0].ndim else 1
        if rows > self.max_batch_size:
            raise ValueError(
                f"request carries {rows} rows > max_batch_size="
                f"{self.max_batch_size}; split it or raise the cap")
        orig_seq = None
        if self.policy.seq_buckets is not None:
            ax = self.policy.seq_axis
            orig_seq = [int(a.shape[ax]) if a.ndim > ax else -1
                        for a in arrs]
            arrs = self.policy.pad_request_seq(arrs)
        req = Request(arrs, rows, self.policy.signature(arrs),
                      orig_seq=orig_seq,
                      timeout_ms=timeout_ms if timeout_ms is not None
                      else self.default_timeout_ms)
        self.metrics.count("submitted")
        try:
            self._batcher.put(req)
        except QueueFullError:
            self.metrics.count("rejected")
            raise
        return req.future

    def submit_many(self, feeds: Sequence[FeedLike],
                    timeout_ms: Optional[float] = None):
        return [self.submit(f, timeout_ms=timeout_ms) for f in feeds]

    # ------------------------------------------------------- warmup
    def bucket_specs(self) -> List[BucketSpec]:
        """The full bucket lattice traffic can land on: power-of-two
        batch buckets up to max_batch_size crossed with the configured
        seq buckets. ``warmup(server.bucket_specs())`` pre-compiles
        everything, so steady state runs compile-free. (With
        ``pad_batch=False`` every row count is its own shape; only the
        max-batch point is returned.)"""
        if self.policy.pad_batch:
            batches, b = [], 1
            while b < self.max_batch_size:
                batches.append(b)
                b <<= 1
            batches.append(self.max_batch_size)
        else:
            batches = [self.max_batch_size]
        seqs = self.policy.seq_buckets or [None]
        return [BucketSpec(b, s) for b in batches for s in seqs]

    def warmup(self, bucket_specs: Optional[Sequence] = None) -> int:
        """Pre-compile the bucket lattice: for each spec — a BucketSpec,
        an int batch bucket, or a (batch, seq) tuple — run one zero
        batch through the predictor so XLA compiles it before traffic
        arrives; defaults to the full ``bucket_specs()`` lattice.
        Returns the number of fresh compiles triggered."""
        if bucket_specs is None:
            bucket_specs = self.bucket_specs()
        specs = []
        for s in bucket_specs:
            if isinstance(s, BucketSpec):
                specs.append(s)
            elif isinstance(s, (tuple, list)):
                specs.append(BucketSpec(*s))
            else:
                specs.append(BucketSpec(int(s)))
        feed_specs = getattr(self.predictor, "_artifact").feeds
        fresh = 0
        for spec in specs:
            arrs = []
            for fs in feed_specs:
                shape = [d if d not in (None, -1) else 1
                         for d in fs["shape"]]
                shape[0] = spec.batch
                ax = self.policy.seq_axis
                if spec.seq is not None and len(shape) > ax:
                    shape[ax] = spec.seq
                arrs.append(np.zeros(tuple(shape), fs["dtype"]))
            sig = self.policy.signature(arrs)
            req = Request(arrs, spec.batch, sig)
            fresh += self._execute([req], record_latency=False)
            req.future.result()    # surface warmup failures loudly
        return fresh

    # ------------------------------------------------------ execution
    def _loop(self):
        self._loop_running = True
        try:
            while True:
                batch = self._batcher.next_batch()
                if batch is None:
                    return
                self._execute(batch)
        finally:
            self._loop_running = False

    def _execute(self, batch: List[Request],
                 record_latency: bool = True) -> int:
        """Run one coalesced batch; resolve every future. Returns 1 on
        a compile-cache miss (a shape XLA had not seen), else 0."""
        from ..profiler import RecordEvent

        rows = sum(r.rows for r in batch)
        padded_rows = self.policy.bucket_batch(rows)
        sig = batch[0].signature
        # padding waste: real input elements vs elements the padded
        # device batch actually carries
        per_row = self.policy.elements_per_row(sig)
        real = sum(int(np.prod(a.shape)) if a.ndim else 1
                   for r in batch for a in r.feeds)
        self.metrics.observe_batch(rows, real, padded_rows * per_row)

        cache_key = (sig, padded_rows)
        miss = cache_key not in self._compiled
        self._compiled.add(cache_key)
        self.metrics.observe_compile(hit=not miss, signature=cache_key)

        feeds_list = [r.feeds for r in batch]
        n_pad = padded_rows - rows
        if n_pad:
            pad_feeds = [np.zeros((n_pad,) + tuple(a.shape[1:]), a.dtype)
                         for a in batch[0].feeds]
            feeds_list = feeds_list + [pad_feeds]
        try:
            with RecordEvent(f"serving::batch[rows={rows}"
                             f",padded={padded_rows}]"):
                results = self.predictor.run_many(feeds_list)
        except Exception as e:  # noqa: BLE001 - fault barrier: the
            # worker thread must survive any model error and fail only
            # the requests of THIS batch
            for r in batch:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
                self.metrics.count("failed")
            return int(miss)
        for r, outs in zip(batch, results):   # padding slice (if any)
            if not r.future.set_running_or_notify_cancel():
                continue                      # cancelled between drain+run
            if r.orig_seq is not None and r.orig_seq[0] > 0:
                # outputs are unpadded against the FIRST feed's original
                # sequence length (the single-sequence-input common case)
                outs = [self.policy.unpad_output(o, r.orig_seq[0])
                        for o in outs]
            r.future.set_result(outs)
            self.metrics.count("completed")
            if record_latency:
                self.metrics.observe_latency(r.latency_ms())
        return int(miss)

    # ------------------------------------------------------ inspection
    @property
    def queue_depth(self) -> int:
        return len(self._batcher)

    def metrics_json(self, indent: Optional[int] = None) -> str:
        return self.metrics.to_json(indent=indent)
