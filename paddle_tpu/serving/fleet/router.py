"""Front-end router: readiness-routed load balancing over N replicas.

``FleetRouter`` is the fleet's single client-facing surface. It keeps
a live view of the replica set (seeded explicitly or discovered from a
``ReplicaSupervisor``), polls every replica's ``/readyz`` on a cadence
(``FLAGS_fleet_health_interval_ms``), and dispatches:

- ``submit`` / ``submit_many`` — the batch is encoded once (codec.py)
  and forwarded WHOLE to one replica, preserving the replica-side
  dynamic batcher's coalescing. Replica choice is least-outstanding
  (the queue-depth signal a heterogeneous fleet needs; with equal
  queues it degrades to round-robin). A shed (HTTP 429 =
  ``QueueFullError``) or an unreachable/not-ready replica triggers a
  retry on a DIFFERENT replica up to ``FLAGS_fleet_retries`` times,
  then the batch fails with ``QueueFullError`` — load shedding
  surfaces to the caller exactly like a single server's backpressure.
- ``submit_generate`` — a streaming decode request: tokens are
  re-emitted into the caller's ``StreamingFuture`` as the replica's
  ndjson stream produces them.

Routing is on READINESS, not liveness: a replica that is alive but
still replaying its warmup manifest receives nothing; the moment its
``/readyz`` flips, traffic flows. In-flight requests on a replica
that dies mid-request fail (only those — no silent cross-replica
retry of possibly-executed work); requests never yet sent to a
replica are always safe to re-route.

``swap_weights`` is the rolling hot swap: one replica at a time is
drained (marked unroutable, outstanding waited to zero), told to
``/reload`` the version-stamped artifact (warm from the shared
compile cache), verified ready again, and returned to rotation —
zero downtime, zero failed in-flight requests, fleet-wide.

Resilience layer (resilience.py, PR 15):

- every request carries an absolute DEADLINE budget: the router
  deducts elapsed time per hop, stamps the remaining milliseconds
  onto the wire (codec deadline trailer / generate JSON field) so the
  worker can reject already-expired work before it ever reaches the
  device, and fails locally once the budget is gone instead of
  burning retries on a dead request;
- each replica has a CIRCUIT BREAKER fed by every dispatch outcome
  (errors, sheds, and — with ``FLAGS_fleet_breaker_latency_ms`` —
  slow-but-alive responses): an open breaker drains the replica even
  while its ``/readyz`` stays green, a half-open probe re-admits it;
- retries use EXPONENTIAL BACKOFF WITH FULL JITTER
  (``FLAGS_fleet_retry_backoff_*``) instead of the fixed immediate
  re-dispatch loop;
- ``submit``/``submit_many`` (idempotent) optionally HEDGE: when the
  primary dispatch exceeds the replica's rolling latency quantile, a
  duplicate fires to a second replica and the first response wins
  (``paddle_fleet_hedges_total`` accounts fired/won/wasted);
  ``submit_generate`` never hedges — a token stream is not
  idempotent.
"""
from __future__ import annotations

import concurrent.futures
import json
import os
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ...observability import tracing
from ..generation.engine import StreamingFuture
from ..request import (DeadlineExceededError, QueueFullError,
                       ServerClosedError)
from . import codec
from .metrics import FleetMetrics, merge_prometheus_texts
from .resilience import (CircuitBreaker, Deadline, latency_quantile,
                         retry_backoff_ms)

__all__ = ["FleetRouter", "RouterApp", "NoReadyReplicaError",
           "ReplicaError"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


# data-plane traffic is always direct to the replica sockets — an
# http_proxy env var must never detour (or break) intra-fleet calls
_OPENER = urllib.request.build_opener(
    urllib.request.ProxyHandler({}))


class NoReadyReplicaError(ServerClosedError):
    """No replica is currently ready to take traffic."""


class ReplicaError(RuntimeError):
    """A replica failed mid-request (connection died after dispatch);
    only the requests riding that connection fail."""


class _Replica:
    """Router-side view of one replica. Mutable fields are guarded by
    the router lock; the breaker carries its own lock."""

    __slots__ = ("replica_id", "url", "outstanding", "ready", "alive",
                 "draining", "version", "errors", "breaker")

    def __init__(self, replica_id, url: str,
                 breaker: Optional[CircuitBreaker] = None):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        self.outstanding = 0
        self.ready = False
        self.alive = False
        self.draining = False
        self.version: Optional[str] = None
        self.errors = 0
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker()


class FleetRouter:
    """Load balancer + swap orchestrator over a replica set.

    ``replicas`` seeds a static ``{id: url}`` map; ``supervisor``
    (optional) is re-polled every health tick so spawned/respawned
    replicas join and dead ones leave automatically — when attached,
    the supervisor is authoritative for the replica set.
    ``start=False`` skips the poll thread (tests drive
    ``poll_replicas()`` explicitly)."""

    def __init__(self, replicas: Optional[Mapping] = None, *,
                 supervisor=None, retries: Optional[int] = None,
                 health_interval_ms: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 pool_size: Optional[int] = None,
                 retry_backoff_ms_: Optional[float] = None,
                 retry_backoff_max_ms: Optional[float] = None,
                 breaker_window: Optional[int] = None,
                 breaker_failure_ratio: Optional[float] = None,
                 breaker_min_samples: Optional[int] = None,
                 breaker_open_ms: Optional[float] = None,
                 breaker_latency_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 hedge_quantile: Optional[float] = None,
                 name: str = "fleet", start: bool = True):
        self.name = name
        self.supervisor = supervisor
        self.retries = int(retries if retries is not None
                           else _flag("FLAGS_fleet_retries", 2))
        self.health_interval_ms = float(
            health_interval_ms if health_interval_ms is not None
            else _flag("FLAGS_fleet_health_interval_ms", 200.0))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else _flag("FLAGS_fleet_request_timeout_s", 120.0))
        self.retry_backoff_ms = float(
            retry_backoff_ms_ if retry_backoff_ms_ is not None
            else _flag("FLAGS_fleet_retry_backoff_ms", 10.0))
        self.retry_backoff_max_ms = float(
            retry_backoff_max_ms if retry_backoff_max_ms is not None
            else _flag("FLAGS_fleet_retry_backoff_max_ms", 500.0))
        self.hedge_ms = float(
            hedge_ms if hedge_ms is not None
            else _flag("FLAGS_fleet_hedge_ms", 0.0))
        self.hedge_quantile = float(
            hedge_quantile if hedge_quantile is not None
            else _flag("FLAGS_fleet_hedge_quantile", 0.95))
        self._breaker_kw = {
            "window": breaker_window,
            "failure_ratio": breaker_failure_ratio,
            "min_samples": breaker_min_samples,
            "open_ms": breaker_open_ms,
            "latency_threshold_ms": breaker_latency_ms,
        }
        self._rng = random.Random()     # backoff jitter
        self.metrics = FleetMetrics(name)
        # stamp this process's spans as the router's (only when nothing
        # else named the process — a worker main() names it first)
        if tracing.process_name().startswith("pid-"):
            tracing.set_process_name(f"router-{name}")
        self._lock = threading.Lock()
        self._replicas: Dict[object, _Replica] = {}
        self._rr = 0                    # round-robin tie-breaker
        self._closed = False
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_wake = threading.Event()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=int(pool_size) if pool_size else 32,
            thread_name_prefix=f"fleet-router-{name}")
        for rid, url in (replicas or {}).items():
            self._replicas[rid] = self._new_replica(rid, url)
        if supervisor is not None:
            self._sync_supervisor()
        self.poll_replicas()            # synchronous first probe
        if start:
            self._start_polling()

    # ------------------------------------------------------ replica set
    def _new_replica(self, replica_id, url: str) -> _Replica:
        rid = str(replica_id)
        breaker = CircuitBreaker(
            on_transition=lambda old, new:
            self.metrics.count_breaker_transition(rid, new),
            **self._breaker_kw)
        return _Replica(replica_id, url, breaker=breaker)

    def add_replica(self, replica_id, url: str):
        with self._lock:
            if replica_id not in self._replicas:
                self._replicas[replica_id] = \
                    self._new_replica(replica_id, url)

    def remove_replica(self, replica_id):
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.metrics.drop_replica(str(replica_id))

    def _sync_supervisor(self):
        eps = self.supervisor.endpoints()
        with self._lock:
            for rid, url in eps.items():
                rep = self._replicas.get(rid)
                if rep is None:
                    self._replicas[rid] = self._new_replica(rid, url)
                elif rep.url != url.rstrip("/"):
                    # respawned under the same id: fresh state (the
                    # breaker resets too — a restarted replica earns
                    # its health record from scratch)
                    self._replicas[rid] = self._new_replica(rid, url)
            for rid in list(self._replicas):
                if rid not in eps:
                    self._replicas.pop(rid)

    def _http(self, url: str, data: Optional[bytes] = None,
              timeout: Optional[float] = None,
              ctype: str = "application/octet-stream"):
        req = urllib.request.Request(
            url, data=data, method="POST" if data is not None
            else "GET")
        if data is not None:
            req.add_header("Content-Type", ctype)
        return _OPENER.open(req,
                            timeout=timeout or self.request_timeout_s)

    def poll_replicas(self):
        """One readiness sweep over the known set (plus a supervisor
        re-sync when attached). The poll thread calls this on its
        cadence; tests and ``wait_ready`` call it directly."""
        if self.supervisor is not None:
            self._sync_supervisor()
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            ready, alive, version = False, False, None
            corrupt = False
            try:
                with self._http(rep.url + "/readyz",
                                timeout=max(
                                    2.0, self.health_interval_ms
                                    / 1e3)) as resp:
                    body = json.loads(resp.read() or b"{}")
                    ready, alive = bool(body.get("ready")), True
                    version = body.get("version")
                    corrupt = bool(body.get("corrupt"))
            except urllib.error.HTTPError as e:
                alive = True            # it answered: alive, not ready
                try:
                    body = json.loads(e.read() or b"{}")
                    version = body.get("version")
                    corrupt = bool(body.get("corrupt"))
                except ValueError:
                    pass
            except Exception:  # noqa: BLE001 - unreachable = not live
                pass
            with self._lock:
                if self._replicas.get(rep.replica_id) is rep:
                    rep.ready, rep.alive = ready, alive
                    if version:
                        rep.version = version
            if corrupt:
                # SDC quarantine: the replica's canary caught silent
                # corruption — not-ready alone still lets the breaker
                # half-open probe traffic back in; force it open so
                # nothing routes there until the episode clears
                rep.breaker.force_open()
        self._update_state_gauges()

    def _update_state_gauges(self):
        with self._lock:
            reps = list(self._replicas.values())
            known = len(reps)
            ready = sum(1 for r in reps
                        if r.ready and not r.draining)
            live = sum(1 for r in reps if r.alive)
            draining = sum(1 for r in reps if r.draining)
        self.metrics.set_replica_states(known, ready, live, draining)

    def _start_polling(self):
        if self._poll_thread is None or \
                not self._poll_thread.is_alive():
            self._poll_thread = threading.Thread(
                target=self._poll_loop,
                name=f"fleet-router-poll-{self.name}", daemon=True)
            self._poll_thread.start()

    def _poll_loop(self):
        while not self._closed:
            self._poll_wake.wait(self.health_interval_ms / 1e3)
            self._poll_wake.clear()
            if self._closed:
                return
            try:
                self.poll_replicas()
            except Exception:  # noqa: BLE001 - the poll loop must
                pass           # survive any replica weirdness

    def wait_ready(self, n: int = 1, timeout: float = 60.0) -> bool:
        """Block until >= n replicas are routable (ready, not
        draining)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll_replicas()
            if len(self._routable()) >= n:
                return True
            time.sleep(0.05)
        return len(self._routable()) >= n

    # ------------------------------------------------------ routing
    def _routable(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.ready and r.alive and not r.draining]

    def _pick(self, exclude: set) -> Optional[_Replica]:
        """Least-outstanding pick over the ready set, breaker-aware:
        candidates are walked best-first and the first whose breaker
        admits a dispatch wins (``allow()`` consumes the half-open
        probe slot only for the replica actually picked — a False
        answer consumes nothing). Returns None when every candidate
        is unready or breaker-shed."""
        with self._lock:
            ready = [r for r in self._replicas.values()
                     if r.ready and r.alive and not r.draining
                     and r.replica_id not in exclude]
            if not ready:
                return None
            self._rr += 1
            rr = self._rr
            # best-first: ascending outstanding, ties rotated so equal
            # queues degrade to round-robin (the pre-breaker behavior)
            by_depth: Dict[int, List[_Replica]] = {}
            for r in ready:
                by_depth.setdefault(r.outstanding, []).append(r)
            ordered: List[_Replica] = []
            for depth in sorted(by_depth):
                tied = by_depth[depth]
                ordered.extend(tied[rr % len(tied):]
                               + tied[:rr % len(tied)])
        for rep in ordered:
            if rep.breaker.allow():
                return rep
        return None

    def _acquire(self, rep: _Replica, n: int):
        with self._lock:
            rep.outstanding += n
            out = rep.outstanding
        self.metrics.set_outstanding(str(rep.replica_id), out)

    def _release(self, rep: _Replica, n: int):
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - n)
            out = rep.outstanding
        self.metrics.set_outstanding(str(rep.replica_id), out)

    def _traced_forward(self, body: bytes, n_req: int,
                        timeout_ms: Optional[float],
                        ctx) -> bytes:
        """``_forward_batch`` under a ``router::request`` root span
        (no-op wrapper when untraced). Failure records an errored root
        span, which tail-promotes an unsampled trace."""
        if ctx is None:
            return self._forward_batch(body, n_req, timeout_ms)
        rctx = ctx.child()
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        attrs = {"router": self.name, "n_req": n_req}
        try:
            payload = self._forward_batch(body, n_req, timeout_ms,
                                          ctx=rctx)
        except BaseException as e:
            tracing.record_span(
                rctx, "router::request", stage="router",
                start_unix_ns=t_wall,
                duration_ms=(time.perf_counter() - t0) * 1e3,
                status="error",
                attrs=dict(attrs,
                           error=f"{type(e).__name__}: {e}"),
                root=True)
            raise
        tracing.record_span(
            rctx, "router::request", stage="router",
            start_unix_ns=t_wall,
            duration_ms=(time.perf_counter() - t0) * 1e3,
            attrs=attrs, root=True)
        return payload

    def _forward_batch(self, body: bytes, n_req: int,
                       timeout_ms: Optional[float],
                       ctx=None) -> bytes:
        """Send one encoded batch to the best replica, with the
        resilient retry policy: breaker-aware pick, exponential
        backoff with full jitter between attempts, optional hedging
        (the batch path is idempotent), and a deadline budget that is
        deducted per hop and stamped onto the wire so the worker
        rejects expired work before dispatch. Returns the raw results
        payload (the HTTP front-end passes it through untouched; the
        Python API decodes it). With ``ctx``, every attempt gets a
        ``router::forward`` span and the batch is stamped with a
        trace trailer so the replica joins the trace."""
        self.metrics.count("routed", n_req)
        # the budget clock starts where the caller handed the work
        # over (submit_many passes a live Deadline so router-pool
        # queueing time counts against it); a raw number means this
        # hop is the ingress
        deadline = timeout_ms if isinstance(timeout_ms, Deadline) \
            else Deadline(timeout_ms)
        attempts = 0
        tried: set = set()
        while True:
            if deadline.expired():
                self.metrics.count_deadline_reject("router", n_req)
                self.metrics.count("failed", n_req)
                raise DeadlineExceededError(
                    f"deadline budget exhausted at the router after "
                    f"{attempts} attempt(s)")
            rep = self._pick(tried)
            if rep is None and tried:
                # every routable replica tried: widen to re-tries
                tried = set()
                rep = self._pick(tried)
            if rep is None:
                self.metrics.count("shed", n_req)
                raise NoReadyReplicaError(
                    "no ready replica (fleet cold, draining, "
                    "breaker-shed, or down)")
            status, value = self._dispatch_maybe_hedged(
                rep, body, n_req, deadline, ctx, attempts, tried)
            if status == "ok":
                self.metrics.count("completed", n_req)
                return value
            if status == "fatal":
                self.metrics.count("failed", n_req)
                raise value
            attempts += 1
            if attempts > self.retries:
                self.metrics.count("shed", n_req)
                raise QueueFullError(
                    f"fleet shed the batch after {attempts} "
                    f"attempts (all replicas at capacity)")
            self.metrics.count_retry(value)
            self._backoff_sleep(attempts, deadline)

    def _backoff_sleep(self, attempt: int, deadline: Deadline):
        """Jittered exponential backoff before retry ``attempt``,
        clamped to the remaining deadline budget."""
        if self.retry_backoff_ms <= 0:
            return
        ms = deadline.clamp_ms(retry_backoff_ms(
            attempt - 1, self.retry_backoff_ms,
            self.retry_backoff_max_ms, self._rng))
        if ms > 0:
            time.sleep(ms / 1e3)

    def _hedge_delay_ms(self, rep: _Replica) -> Optional[float]:
        """How long to let ``rep``'s dispatch run before hedging to a
        second replica: the ``FLAGS_fleet_hedge_quantile`` of the
        PEER replicas' rolling latency windows (the potential hedge
        targets — "someone else would usually have answered by now"),
        floored by ``FLAGS_fleet_hedge_ms``. Keying on the peers
        rather than the primary's own window matters: a uniformly
        slow primary would otherwise inflate its own trigger and
        never get hedged around. None = hedging off."""
        if self.hedge_ms <= 0:
            return None
        with self._lock:
            peers = [r for r in self._replicas.values()
                     if r is not rep and r.ready and r.alive
                     and not r.draining]
        samples: List[float] = []
        for p in peers:
            samples.extend(p.breaker.latency_window())
        q = latency_quantile(samples, self.hedge_quantile)
        return max(self.hedge_ms, q) if q is not None \
            else self.hedge_ms

    def _dispatch_maybe_hedged(self, rep: _Replica, body: bytes,
                               n_req: int, deadline: Deadline, ctx,
                               attempt: int, tried: set):
        """One retry attempt, possibly covered by a hedge: when the
        primary dispatch is still pending past the hedge delay, a
        duplicate fires to a second replica and the FIRST success
        wins (submit/submit_many are idempotent — duplicate execution
        is waste, not corruption; the loser's connection is closed
        and its eventual completion is accounted as wasted work).
        Returns ``(status, value)`` like ``_dispatch_once``; failed
        replicas are added to ``tried``."""
        delay_ms = self._hedge_delay_ms(rep)
        if delay_ms is None:
            res = self._dispatch_once(rep, body, n_req, deadline,
                                      ctx, attempt)
            if res[0] != "ok":
                tried.add(rep.replica_id)
            return res
        decided = threading.Event()
        progress = threading.Event()
        lock = threading.Lock()
        results: Dict[str, tuple] = {}
        cancels = {"primary": {"resp": None, "cancelled": False},
                   "hedge": {"resp": None, "cancelled": False}}

        def _runner(key, target_rep, hedged):
            res = self._dispatch_once(
                target_rep, body, n_req, deadline, ctx, attempt,
                hedge=hedged, cancel_box=cancels[key])
            with lock:
                results[key] = res
                late = decided.is_set()
            if late and res[0] == "ok":
                # the loser completed successfully after the winner
                # was returned (cancellation can only abort a leg
                # whose response had started arriving): duplicate
                # execution, paid for nothing
                self.metrics.count_hedge("wasted")
            progress.set()

        threading.Thread(target=_runner, args=("primary", rep, False),
                         daemon=True,
                         name=f"fleet-dispatch-{self.name}").start()
        hedge_rep: Optional[_Replica] = None
        waited_hedge_delay = False
        while True:
            wait_s = None
            if not waited_hedge_delay and hedge_rep is None:
                wait_s = deadline.clamp_ms(delay_ms) / 1e3 \
                    if deadline.bounded else delay_ms / 1e3
            fired = progress.wait(wait_s)
            progress.clear()
            if not fired and hedge_rep is None:
                # primary still pending past the hedge delay
                waited_hedge_delay = True
                hedge_rep = self._pick(tried | {rep.replica_id})
                if hedge_rep is None:
                    continue    # nobody to hedge to: wait primary out
                self.metrics.count_hedge("fired")
                threading.Thread(
                    target=_runner, args=("hedge", hedge_rep, True),
                    daemon=True,
                    name=f"fleet-hedge-{self.name}").start()
                continue
            with lock:
                p = results.get("primary")
                h = results.get("hedge")
                if p is not None and p[0] == "ok":
                    decided.set()
                elif h is not None and h[0] == "ok":
                    decided.set()
                elif p is not None and \
                        (hedge_rep is None or h is not None):
                    decided.set()   # everything launched has failed
            if not decided.is_set():
                continue
            if p is not None and p[0] == "ok":
                self._cancel_loser(cancels["hedge"])
                return p
            if h is not None and h[0] == "ok":
                self.metrics.count_hedge("won")
                self._cancel_loser(cancels["primary"])
                return h
            # both (or the only) dispatch failed: prefer the fatal
            # outcome — it must surface, not be retried away
            tried.add(rep.replica_id)
            if hedge_rep is not None and h is not None:
                tried.add(hedge_rep.replica_id)
            if p is not None and p[0] == "fatal":
                return p
            if h is not None and h[0] == "fatal":
                return h
            return p if p is not None else h

    @staticmethod
    def _cancel_loser(cancel_box: dict):
        """Abort the losing hedge leg: mark it cancelled (so its
        failure is not charged to the replica's breaker) and close
        its in-flight response to stop the transfer."""
        cancel_box["cancelled"] = True
        resp = cancel_box.get("resp")
        if resp is not None:
            try:
                resp.close()
            except OSError:
                pass

    def _dispatch_once(self, rep: _Replica, body: bytes, n_req: int,
                       deadline: Deadline, ctx, attempt: int,
                       hedge: bool = False,
                       cancel_box: Optional[dict] = None):
        """One HTTP dispatch of an encoded batch to one replica,
        classified: ``("ok", payload)``, ``("retry", reason)`` for a
        shed/unavailable outcome another replica can absorb, or
        ``("fatal", exc)`` for a mid-request death (work may have
        executed — never silently re-run outside a hedge). Records
        the outcome on the replica's breaker and, when traced, emits
        the per-attempt ``router::forward`` span."""
        remaining = deadline.remaining_ms()
        suffix = "/submit_many" if remaining is None \
            else f"/submit_many?timeout_ms={remaining}"
        # the socket timeout is bounded by the budget too (plus slack
        # for the worker's own typed rejection to travel back): a
        # hung replica must not hold an already-dead request for the
        # full FLAGS_fleet_request_timeout_s
        http_timeout = None if remaining is None else \
            min(self.request_timeout_s,
                max(0.05, remaining / 1e3 + 0.25))
        self._acquire(rep, n_req)
        fctx = ctx.child() if ctx is not None else None
        send_body = codec.attach_trace_trailer(
            body, [fctx.to_traceparent()] * n_req) \
            if fctx is not None else body
        if remaining is not None:
            send_body = codec.attach_deadline_trailer(
                send_body, [remaining] * n_req)
        span_status, span_err = "ok", None
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        try:
            try:
                resp = self._http(rep.url + suffix, data=send_body,
                                  timeout=http_timeout,
                                  ctype="application/x-paddle-fleet")
                if cancel_box is not None:
                    cancel_box["resp"] = resp
                with resp:
                    payload = resp.read()
                ms = (time.perf_counter() - t0) * 1e3
                rep.breaker.record(True, ms)
                self.metrics.observe_latency(ms)
                if ctx is not None:
                    tracing.record_exemplar(
                        "paddle_fleet_request_ms", ms, ctx.trace_id)
                return ("ok", payload)
            except urllib.error.HTTPError as e:
                e.read()
                rep.breaker.record(False)
                if e.code == 429:   # replica shed the whole batch
                    self.metrics.count_shed(str(rep.replica_id))
                    reason = "queue_full"
                elif e.code == 503:  # closed/not ready after all
                    with self._lock:
                        rep.ready = False
                    reason = "unavailable"
                else:
                    span_status, span_err = "error", f"HTTP {e.code}"
                    return ("fatal", ReplicaError(
                        f"replica {rep.replica_id} returned HTTP "
                        f"{e.code}"))
                span_status, span_err = "error", reason
                return ("retry", reason)
            except (ConnectionRefusedError, urllib.error.URLError,
                    ConnectionResetError, TimeoutError,
                    ValueError, OSError) as e:
                if cancel_box is not None and \
                        cancel_box.get("cancelled"):
                    # the hedge race was decided against this leg and
                    # its connection was closed under it: not the
                    # replica's fault, nothing to record or report
                    span_status, span_err = "error", "hedge_cancelled"
                    return ("retry", "cancelled")
                # Refused before the request was read: nothing
                # executed, safe to re-route. Anything after dispatch
                # may have executed — fatal, don't double-run (a
                # HEDGE may still cover it: duplicate execution of
                # the idempotent batch path is explicitly allowed).
                refused = isinstance(e, ConnectionRefusedError) or \
                    isinstance(getattr(e, "reason", None),
                               ConnectionRefusedError)
                rep.breaker.record(False)
                with self._lock:
                    rep.alive = refused and rep.alive
                    rep.ready = False
                span_status = "error"
                span_err = f"{type(e).__name__}: {e}"
                if not refused:
                    return ("fatal", ReplicaError(
                        f"replica {rep.replica_id} died mid-request: "
                        f"{type(e).__name__}: {e}"))
                return ("retry", "unavailable")
        finally:
            self._release(rep, n_req)
            if fctx is not None:
                f_attrs = {"replica": str(rep.replica_id),
                           "attempt": attempt}
                if hedge:
                    f_attrs["hedge"] = True
                if span_err:
                    f_attrs["error"] = span_err
                tracing.record_span(
                    fctx, "router::forward", stage="forward",
                    start_unix_ns=t_wall,
                    duration_ms=(time.perf_counter() - t0) * 1e3,
                    status=span_status, attrs=f_attrs, root=True)

    # ------------------------------------------------------ client API
    def submit(self, feed, timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None):
        """One request -> Future of its output-array list (the
        ``InferenceServer.submit`` contract, fleet-wide)."""
        return self.submit_many([feed], timeout_ms=timeout_ms,
                                tenant=tenant)[0]

    def submit_many(self, feeds: Sequence,
                    timeout_ms: Optional[float] = None,
                    tenant: Optional[str] = None):
        """Bulk submit: the batch rides ONE replica dispatch (the
        replica's dynamic batcher coalesces it further). Returns one
        Future per request; per-request replica-side failures resolve
        individual futures, a fleet-wide shed fails them all with
        QueueFullError."""
        if self._closed:
            raise ServerClosedError("router is shut down")
        norm = []
        for f in feeds:
            if isinstance(f, dict):
                raise TypeError(
                    "fleet submit takes positional feed lists "
                    "(ordered like the model's inputs); dict feeds "
                    "are a single-process InferenceServer feature")
            norm.append([np.asarray(a) for a in f]
                        if isinstance(f, (list, tuple))
                        else [np.asarray(f)])
        body = codec.encode_batch(norm)
        if tenant is not None:
            # tenancy rides the wire as the PDTN trailer next to
            # PDTC/PDDL; the worker's admission gate reads it back
            body = codec.attach_tenant_trailer(
                body, [tenant] * len(norm))
        futs = [concurrent.futures.Future() for _ in norm]
        # trace identity is captured on the CALLER's thread (ambient
        # context or a fresh sampled one); the whole batch rides one
        # trace — the single-request submit() case is the 1:1 trace
        # the /tracez recipe documents
        ctx = tracing.request_context()
        # the deadline budget clock also starts HERE, on the caller's
        # thread — router-pool queueing time is part of the budget
        deadline = Deadline(timeout_ms)

        def _run():
            try:
                payload = self._traced_forward(body, len(norm),
                                               deadline, ctx)
                results = codec.decode_results(payload)
                if len(results) != len(futs):
                    raise ReplicaError(
                        f"replica answered {len(results)} results "
                        f"for {len(futs)} requests")
            except BaseException as e:  # noqa: BLE001 - fail them all
                for f in futs:
                    if f.set_running_or_notify_cancel():
                        f.set_exception(e)
                return
            for f, res in zip(futs, results):
                if not f.set_running_or_notify_cancel():
                    continue
                if isinstance(res, BaseException):
                    f.set_exception(res)
                else:
                    f.set_result(res)

        self._pool.submit(_run)
        return futs

    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        temperature: float = 0.0,
                        timeout_ms: Optional[float] = None,
                        seed: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        tenant: Optional[str] = None
                        ) -> StreamingFuture:
        """Fleet-wide ``GenerationServer.submit_generate``: tokens
        stream back through the returned future as the chosen
        replica's decode loop emits them. ``timeout_ms`` is the
        replica-side SCHEDULING deadline (queued too long = dropped
        unrun); ``deadline_ms`` is the end-to-end HARD budget — the
        router deducts its own elapsed time before dispatch and the
        engine evicts the stream (pages freed) when the budget
        expires mid-generation. ``cancel()`` on the returned future
        propagates to the replica: the stream connection is closed so
        the engine evicts the sequence instead of decoding into a
        dead socket. Never hedged — a token stream is not
        idempotent."""
        if self._closed:
            raise ServerClosedError("router is shut down")
        fut = StreamingFuture()
        ctx = tracing.request_context()
        gctx = ctx.child() if ctx is not None else None
        req = {
            "prompt": [int(t) for t in np.asarray(prompt).ravel()],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "timeout_ms": timeout_ms, "seed": seed}
        if tenant is not None:
            req["tenant"] = str(tenant)
        if gctx is not None:
            req["traceparent"] = gctx.to_traceparent()
        deadline = Deadline(deadline_ms)
        self.metrics.count("routed")
        self._pool.submit(self._run_generate_traced, req, fut, gctx,
                          deadline)
        return fut

    def _run_generate_traced(self, req: dict, fut: StreamingFuture,
                             gctx=None,
                             deadline: Optional[Deadline] = None):
        """``_run_generate`` under a ``router::generate`` root span
        whose status mirrors the stream's outcome."""
        if gctx is None:
            return self._run_generate(req, fut, deadline)
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        self._run_generate(req, fut, deadline)
        exc = fut.exception()
        reason = fut.finish_reason
        attrs = {"router": self.name,
                 "finish_reason": reason or ""}
        if exc is not None:
            attrs["error"] = f"{type(exc).__name__}: {exc}"
        tracing.record_span(
            gctx, "router::generate", stage="router",
            start_unix_ns=t_wall,
            duration_ms=(time.perf_counter() - t0) * 1e3,
            status="error" if exc is not None else "ok",
            attrs=attrs, root=True)

    def _run_generate(self, req: dict, fut: StreamingFuture,
                      deadline: Optional[Deadline] = None):
        deadline = deadline or Deadline.never()
        tried: set = set()
        for attempt in range(self.retries + 1):
            if deadline.expired():
                self.metrics.count_deadline_reject("router")
                self.metrics.count("failed")
                fut._fail(DeadlineExceededError(
                    "deadline budget exhausted at the router"),
                    reason="deadline")
                return
            rep = self._pick(tried)
            if rep is None:
                tried = set()
                rep = self._pick(tried)
            if rep is None:
                self.metrics.count("shed")
                fut._fail(NoReadyReplicaError("no ready replica"),
                          reason="shed")
                return
            # the replica sees what is LEFT of the budget, not what
            # the caller started with — elapsed router time (queueing,
            # earlier attempts, backoff) is already deducted
            body_req = dict(req)
            remaining = deadline.remaining_ms()
            if remaining is not None:
                body_req["deadline_ms"] = remaining
            body = json.dumps(body_req).encode()
            self._acquire(rep, 1)
            emitted = False
            try:
                resp = self._http(rep.url + "/generate", data=body,
                                  ctype="application/json")
                # cancel propagation: closing the stream's socket is
                # the cancel signal the replica can actually observe —
                # its next token write fails, the worker cancels the
                # engine future, the engine evicts the sequence and
                # frees its pages
                fut._set_cancel_hook(resp.close)
                try:
                    with resp:
                        for line in resp:
                            if fut._cancel_requested:
                                fut._finish("cancelled")
                                return
                            ev = json.loads(line)
                            if ev.get("done"):
                                reason = ev.get("finish_reason",
                                                "eos")
                                if ev.get("error"):
                                    # deadline evictions stay TYPED
                                    # across the wire, like the
                                    # batch codec's status codes
                                    exc = DeadlineExceededError(
                                        ev["error"]) \
                                        if reason == "deadline" \
                                        else ReplicaError(ev["error"])
                                    fut._fail(exc, reason=reason)
                                else:
                                    fut._finish(reason)
                                self.metrics.count("completed")
                                rep.breaker.record(True)
                                return
                            emitted = True
                            fut._emit(int(ev["t"]))
                finally:
                    fut._set_cancel_hook(None)
                if fut._cancel_requested:
                    # the cancel hook closed the socket under the
                    # reader: a clean cancellation, not a dead replica
                    fut._finish("cancelled")
                    return
                # stream closed without a terminal event: the replica
                # died mid-stream
                raise ReplicaError(
                    f"replica {rep.replica_id} closed the stream "
                    f"mid-generation")
            except urllib.error.HTTPError as e:
                e.read()
                rep.breaker.record(False)
                if e.code in (429, 503) and not emitted:
                    self.metrics.count_retry(
                        "queue_full" if e.code == 429
                        else "unavailable")
                    if e.code == 429:
                        self.metrics.count_shed(str(rep.replica_id))
                    tried.add(rep.replica_id)
                    self._backoff_sleep(attempt + 1, deadline)
                    continue
                self.metrics.count("failed")
                fut._fail(QueueFullError(f"HTTP {e.code}")
                          if e.code == 429
                          else ReplicaError(f"HTTP {e.code}"),
                          reason="error")
                return
            except BaseException as e:  # noqa: BLE001 - tokens may
                # already be consumed: never silently re-run the
                # stream on another replica
                if fut._cancel_requested:
                    # socket torn down by the cancel hook mid-read
                    fut._finish("cancelled")
                    return
                rep.breaker.record(False)
                if not emitted and isinstance(
                        e, (ConnectionRefusedError,
                            urllib.error.URLError)):
                    with self._lock:
                        rep.ready = False
                    self.metrics.count_retry("unavailable")
                    tried.add(rep.replica_id)
                    self._backoff_sleep(attempt + 1, deadline)
                    continue
                self.metrics.count("failed")
                fut._fail(ReplicaError(
                    f"replica {rep.replica_id} stream failed: "
                    f"{type(e).__name__}: {e}"), reason="error")
                return
            finally:
                self._release(rep, 1)
        self.metrics.count("shed")
        fut._fail(QueueFullError(
            f"fleet shed the stream after {self.retries + 1} "
            f"attempts"), reason="shed")

    # ------------------------------------------------------ hot swap
    def swap_weights(self, model_prefix: str, *,
                     drain_timeout_s: Optional[float] = None,
                     ready_timeout_s: float = 300.0) -> dict:
        """Rolling hot weight swap: drain -> /reload -> ready, one
        replica at a time. Raises on the first failed replica (the
        already-swapped ones keep the new weights, the rest keep the
        old — the fleet stays serviceable either way); the drained
        replica is always returned to rotation."""
        drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _flag("FLAGS_fleet_drain_timeout_s", 30.0))
        report = {"model_prefix": str(model_prefix), "replicas": []}
        with self._lock:
            order = sorted(self._replicas,
                           key=lambda rid: str(rid))
        for rid in order:
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or not rep.alive:
                    continue
                rep.draining = True
            self._update_state_gauges()
            t0 = time.perf_counter()
            try:
                self._drain_one(rep, drain_timeout_s)
                t_drained = time.perf_counter()
                with self._http(
                        rep.url + "/reload",
                        data=json.dumps(
                            {"model_prefix": str(model_prefix)}
                        ).encode(),
                        ctype="application/json",
                        timeout=ready_timeout_s) as resp:
                    version = json.loads(resp.read()).get("version")
                self._await_ready(rep, ready_timeout_s)
                self.metrics.count_swap("replica_reloaded")
                report["replicas"].append({
                    "replica": str(rid), "version": version,
                    "drain_ms": round((t_drained - t0) * 1e3, 1),
                    "reload_ms": round(
                        (time.perf_counter() - t_drained) * 1e3, 1)})
            except BaseException:
                self.metrics.count_swap("failed")
                raise
            finally:
                with self._lock:
                    rep.draining = False
                self._update_state_gauges()
        self.metrics.count_swap("completed")
        return report

    def _drain_one(self, rep: _Replica, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if rep.outstanding == 0:
                    return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {rep.replica_id} still has "
                    f"{rep.outstanding} outstanding after "
                    f"{timeout_s}s drain")
            time.sleep(0.002)

    def _await_ready(self, rep: _Replica, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with self._http(rep.url + "/readyz",
                                timeout=5.0) as resp:
                    if json.loads(resp.read()).get("ready"):
                        with self._lock:
                            rep.ready, rep.alive = True, True
                        return
            except Exception:  # noqa: BLE001 - keep polling until the
                pass           # deadline decides
            time.sleep(0.02)
        raise TimeoutError(
            f"replica {rep.replica_id} not ready again within "
            f"{timeout_s}s of reload")

    # ------------------------------------------------------ inspection
    def replica_states(self) -> List[dict]:
        with self._lock:
            reps = list(self._replicas.values())
        return [{"replica": str(r.replica_id), "url": r.url,
                 "ready": r.ready, "alive": r.alive,
                 "draining": r.draining,
                 "outstanding": r.outstanding,
                 "version": r.version,
                 "breaker": r.breaker.snapshot()}
                for r in reps]

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def merged_metrics(self) -> str:
        """Fleet-wide Prometheus scrape: this process's registry plus
        every live replica's /metrics re-labeled with its id."""
        from ...observability import default_registry, prometheus_text
        texts = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/metrics",
                                timeout=5.0) as resp:
                    texts[rid] = resp.read().decode()
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # just drops out of the merged view
        return merge_prometheus_texts(
            texts, own=prometheus_text(default_registry()))

    def merged_tracez(self, trace_id: Optional[str] = None,
                      min_duration_ms: Optional[float] = None,
                      limit: int = 100) -> dict:
        """Fleet-wide ``/tracez``: this process's flight recorder plus
        every live replica's, stitched by trace id — the router span,
        the worker span, and the engine's queue/assembly/dispatch/
        device/fetch children come back as ONE trace."""
        remote: List[dict] = []
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        q = f"?limit={int(limit)}"
        if trace_id:
            q += f"&trace_id={trace_id}"
        for rid, url in reps:
            try:
                with self._http(url + "/tracez" + q,
                                timeout=5.0) as resp:
                    doc = json.loads(resp.read())
                for t in doc.get("traces", []):
                    remote.extend(t.get("spans", []))
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        return tracing.tracez_payload(
            trace_id=trace_id, min_duration_ms=min_duration_ms,
            limit=limit, extra_spans=remote)

    def merged_sloz(self) -> dict:
        """Fleet-wide ``/sloz``: this process's SLO evaluation plus
        every live replica's, with rolling-window good/total counts
        summed per (SLO, window) — fleet attainment, the way
        ``merged_tracez`` stitches spans."""
        from ...observability import slo as slo_mod
        own = slo_mod.sloz_payload()
        remotes: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/sloz", timeout=5.0) as resp:
                    remotes[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        return slo_mod.merge_sloz_payloads(own, remotes)

    def merged_schedz(self) -> dict:
        """Fleet-wide ``/schedz``: this process's admission/autoscaler
        state plus every live replica's, with per-tenant admission
        event counts summed fleet-wide — one scrape answers "who is
        being shed, where, and what did the autoscaler last do"."""
        from ..scheduling import schedz as schedz_mod
        own = schedz_mod.schedz_payload()
        remotes: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/schedz", timeout=5.0) as resp:
                    remotes[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        return schedz_mod.merge_schedz_payloads(own, remotes)

    def merged_execz(self) -> dict:
        """Fleet-wide ``/execz``: this process's executable registry
        plus every live replica's, keyed by replica id, with a
        fleet-level per-site rollup — which replica is running which
        executables at what cost, one page."""
        from ...observability import xstats
        own = xstats.execz_payload()
        replicas: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/execz", timeout=10.0) as resp:
                    replicas[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        fleet_sites: Dict[str, dict] = {}
        for payload in replicas.values():
            for site, s in (payload.get("sites") or {}).items():
                agg = fleet_sites.setdefault(
                    site, {"entries": 0, "dispatches": 0, "flops": 0.0})
                agg["entries"] += s.get("entries", 0)
                agg["dispatches"] += s.get("dispatches", 0)
                agg["flops"] = max(agg["flops"], s.get("flops", 0.0))
        return {"router": own, "replicas": replicas,
                "fleet": {"sites": fleet_sites,
                          "replicas_merged": len(replicas)}}

    def merged_numericsz(self) -> dict:
        """Fleet-wide ``/numericsz``: this process's numerics plane
        plus every live replica's, keyed by replica id, with a fleet
        rollup — total anomalies, canary failures, the corrupted
        replica set, and the worst (lowest) finite fraction seen —
        so one page answers "is anything on this fleet corrupting"."""
        from ...observability import numerics
        own = numerics.numericsz_payload()
        replicas: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/numericsz",
                                timeout=10.0) as resp:
                    replicas[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        anomalies = 0
        canary_failures = 0
        corrupt: List[str] = []
        min_frac = 1.0
        for rid, payload in replicas.items():
            an = payload.get("anomalies") or {}
            anomalies += int(an.get("total") or 0)
            cn = payload.get("canary") or {}
            canary_failures += int(cn.get("failures") or 0)
            if cn.get("corrupt"):
                corrupt.append(rid)
            for s in (payload.get("serving") or {}).values():
                f = s.get("finite_fraction")
                if f is not None:
                    min_frac = min(min_frac, float(f))
        return {"router": own, "replicas": replicas,
                "fleet": {"replicas_merged": len(replicas),
                          "anomalies_total": anomalies,
                          "canary_failures_total": canary_failures,
                          "corrupt_replicas": sorted(corrupt),
                          "min_finite_fraction": min_frac}}

    def merged_profilez(self, duration_ms: Optional[float] = None
                        ) -> dict:
        """Fleet-wide ``/profilez``: without a duration, every live
        replica's capture ring; with one, fan a bounded capture out to
        ALL live replicas concurrently and return the stitched bundle
        of chrome-trace documents keyed by replica id."""
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        q = f"?duration_ms={float(duration_ms)}" if duration_ms else ""

        def one(url):
            timeout = 10.0 + (float(duration_ms) / 1e3
                              if duration_ms else 0.0)
            with self._http(url + "/profilez" + q,
                            timeout=timeout) as resp:
                return json.loads(resp.read())

        replicas: Dict[str, dict] = {}
        futs = {rid: self._pool.submit(one, url) for rid, url in reps}
        for rid, fut in futs.items():
            try:
                replicas[rid] = fut.result()
            except Exception as e:  # noqa: BLE001 - a refused or dead
                replicas[rid] = {"error": repr(e)}  # replica is still
                # part of the bundle: the operator sees who failed
        return {"replicas": replicas,
                "captured": duration_ms is not None,
                "replicas_merged": len(replicas)}

    def statusz(self) -> dict:
        """Fleet status page: per-replica id/readiness/outstanding/
        version (+ restart counts when a supervisor is attached) and
        the router's own counters — the single-server ``/statusz``
        parity view for a fleet."""
        replicas = self.replica_states()
        restarts = {}
        if self.supervisor is not None:
            try:
                restarts = {str(k): v for k, v in
                            self.supervisor.restart_counts().items()}
            except Exception:  # noqa: BLE001 - status must not 500 on
                pass           # a half-stopped supervisor
        for r in replicas:
            r["restarts"] = restarts.get(r["replica"], 0)
        return {
            "router": self.name,
            "pid": os.getpid(),
            "replicas": replicas,
            "ready_replicas": sum(1 for r in replicas
                                  if r["ready"] and not r["draining"]),
            "restarts_total": sum(restarts.values()),
            "metrics": self.metrics_snapshot(),
        }

    # ------------------------------------------------------ lifecycle
    def shutdown(self):
        self._closed = True
        self._poll_wake.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


# ---------------------------------------------------------------- http
class _RouterHandler(BaseHTTPRequestHandler):
    """HTTP front-end over a FleetRouter: the external data plane.

    ``POST /submit_many`` and ``POST /generate`` speak the replica
    wire protocol (codec.py / ndjson) and PASS THE BODY THROUGH — the
    router never decodes the arrays, so its per-request CPU cost is a
    replica pick plus a socket copy. Serving-layer errors map to the
    same status codes replicas use (429 shed, 503 no ready replica),
    so a client cannot tell one server from a fleet."""

    server_version = "paddle-tpu-fleet-router/1.0"

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @property
    def _router(self) -> FleetRouter:
        return self.server.router  # type: ignore[attr-defined]

    def log_message(self, *args):
        pass

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                from ...observability import (default_registry,
                                              prometheus_text)
                from ...observability.exposition import \
                    PROMETHEUS_CONTENT_TYPE
                text = self._router.merged_metrics() \
                    if "merged=1" in query \
                    else prometheus_text(default_registry())
                self._send(200, text.encode(),
                           PROMETHEUS_CONTENT_TYPE)
            elif path == "/healthz":
                live = any(s["alive"]
                           for s in self._router.replica_states())
                self._send(200 if live else 503, json.dumps(
                    {"ok": live,
                     "replicas": self._router.replica_states()},
                    sort_keys=True).encode())
            elif path == "/readyz":
                n = len(self._router._routable())
                self._send(200 if n else 503, json.dumps(
                    {"ready": bool(n),
                     "ready_replicas": n}).encode())
            elif path == "/statusz":
                self._send(200, json.dumps(
                    self._router.statusz(),
                    sort_keys=True, default=str).encode())
            elif path == "/tracez":
                from urllib.parse import parse_qs
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                doc = self._router.merged_tracez(
                    trace_id=q.get("trace_id") or None,
                    min_duration_ms=float(q["min_ms"])
                    if q.get("min_ms") else None,
                    limit=int(q.get("limit", 100)))
                if q.get("format") == "chrome":
                    from ...observability import tracing as _tracing
                    spans = [s for t in doc["traces"]
                             for s in t["spans"]]
                    doc = {"traceEvents":
                           _tracing.chrome_trace_events(spans)}
                self._send(200, json.dumps(doc, sort_keys=True,
                                           default=str).encode())
            elif path == "/sloz":
                self._send(200, json.dumps(
                    self._router.merged_sloz(), sort_keys=True,
                    default=str).encode())
            elif path == "/schedz":
                self._send(200, json.dumps(
                    self._router.merged_schedz(), sort_keys=True,
                    default=str).encode())
            elif path == "/goodputz":
                from ...observability.goodput import goodputz_payload
                self._send(200, json.dumps(
                    goodputz_payload(), sort_keys=True).encode())
            elif path == "/execz":
                self._send(200, json.dumps(
                    self._router.merged_execz(), sort_keys=True,
                    default=str).encode())
            elif path == "/numericsz":
                self._send(200, json.dumps(
                    self._router.merged_numericsz(), sort_keys=True,
                    default=str).encode())
            elif path == "/profilez":
                from urllib.parse import parse_qs
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                doc = self._router.merged_profilez(
                    duration_ms=float(q["duration_ms"])
                    if q.get("duration_ms") else None)
                self._send(200, json.dumps(doc, sort_keys=True,
                                           default=str).encode())
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as e:  # noqa: BLE001 - handler fault barrier
            try:
                self._send(500, f"{e!r}\n".encode(), "text/plain")
            except Exception:  # noqa: BLE001
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else b""
            if path == "/submit_many":
                timeout_ms = None
                for part in query.split("&"):
                    if part.startswith("timeout_ms="):
                        timeout_ms = \
                            float(part.split("=", 1)[1]) or None
                # external ingress deadline: the x-paddle-deadline-ms
                # header is the caller's REMAINING budget (the header
                # twin of the codec deadline trailer); it wins over
                # the scheduling timeout as the propagated budget
                hdr = self.headers.get("x-paddle-deadline-ms")
                if hdr:
                    try:
                        timeout_ms = float(hdr) or None
                    except ValueError:
                        pass
                n_req = codec.peek_batch_size(body)
                # tenant ingress: the x-paddle-tenant header is the
                # header twin of the PDTN trailer. attach is
                # idempotent — a trailer already stamped upstream wins
                t_hdr = self.headers.get("x-paddle-tenant")
                if t_hdr:
                    body = codec.attach_tenant_trailer(
                        body, [t_hdr] * n_req)
                # external ingress: honor the caller's traceparent
                # header, else make the head-sampling decision here
                ctx = tracing.parse_traceparent(
                    self.headers.get("traceparent")) or \
                    tracing.request_context()
                payload = self._router._traced_forward(
                    body, n_req, timeout_ms, ctx)
                self._send(200, payload, "application/x-paddle-fleet")
            elif path == "/generate":
                self._generate(body)
            else:
                self._send(404, b"not found\n", "text/plain")
        except DeadlineExceededError as e:
            self._send(504, f"{e}\n".encode(), "text/plain")
        except NoReadyReplicaError as e:
            self._send(503, f"{e}\n".encode(), "text/plain")
        except QueueFullError as e:
            self._send(429, f"{e}\n".encode(), "text/plain")
        except codec.CodecError as e:
            self._send(400, f"{e}\n".encode(), "text/plain")
        except Exception as e:  # noqa: BLE001 - handler fault barrier
            try:
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain")
            except Exception:  # noqa: BLE001
                pass

    def _generate(self, body: bytes):
        req = json.loads(body or b"{}")
        ctx = tracing.parse_traceparent(
            req.get("traceparent")
            or self.headers.get("traceparent"))
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is None:
            hdr = self.headers.get("x-paddle-deadline-ms")
            if hdr:
                try:
                    deadline_ms = float(hdr) or None
                except ValueError:
                    pass
        # tenant: the JSON field wins over the x-paddle-tenant header
        tenant = req.get("tenant") or \
            self.headers.get("x-paddle-tenant")
        with tracing.use_context(ctx):
            fut = self._router.submit_generate(
                req["prompt"],
                max_new_tokens=int(req.get("max_new_tokens", 32)),
                temperature=float(req.get("temperature", 0.0)),
                timeout_ms=req.get("timeout_ms"),
                seed=req.get("seed"),
                deadline_ms=deadline_ms,
                tenant=tenant)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        try:
            for tok in fut:
                self.wfile.write(
                    json.dumps({"t": int(tok)}).encode() + b"\n")
                self.wfile.flush()
            self.wfile.write(json.dumps(
                {"done": True,
                 "finish_reason": fut.finish_reason}).encode() + b"\n")
        except BrokenPipeError:
            fut.cancel()
        except BaseException as e:  # noqa: BLE001 - stream the error
            reason = "deadline" \
                if isinstance(e, DeadlineExceededError) else "error"
            try:
                self.wfile.write(json.dumps(
                    {"done": True, "finish_reason": reason,
                     "error": f"{type(e).__name__}: {e}"}).encode()
                    + b"\n")
            except OSError:
                pass


class RouterApp:
    """The router's HTTP front-end on a daemon thread (same shape as
    worker.ReplicaApp). ``port=0`` binds ephemeral."""

    def __init__(self, router: FleetRouter, host: str = "0.0.0.0",
                 port: int = 0):
        self.router = router
        self.host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path: str = "") -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "::") \
            else self.host
        return f"http://{host}:{self.port}{path}"

    def start(self) -> "RouterApp":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _RouterHandler)
        httpd.daemon_threads = True
        httpd.router = self.router      # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"fleet-router-http-{self.router.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
