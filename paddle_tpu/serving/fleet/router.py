"""Front-end router: readiness-routed load balancing over N replicas.

``FleetRouter`` is the fleet's single client-facing surface. It keeps
a live view of the replica set (seeded explicitly or discovered from a
``ReplicaSupervisor``), polls every replica's ``/readyz`` on a cadence
(``FLAGS_fleet_health_interval_ms``), and dispatches:

- ``submit`` / ``submit_many`` — the batch is encoded once (codec.py)
  and forwarded WHOLE to one replica, preserving the replica-side
  dynamic batcher's coalescing. Replica choice is least-outstanding
  (the queue-depth signal a heterogeneous fleet needs; with equal
  queues it degrades to round-robin). A shed (HTTP 429 =
  ``QueueFullError``) or an unreachable/not-ready replica triggers a
  retry on a DIFFERENT replica up to ``FLAGS_fleet_retries`` times,
  then the batch fails with ``QueueFullError`` — load shedding
  surfaces to the caller exactly like a single server's backpressure.
- ``submit_generate`` — a streaming decode request: tokens are
  re-emitted into the caller's ``StreamingFuture`` as the replica's
  ndjson stream produces them.

Routing is on READINESS, not liveness: a replica that is alive but
still replaying its warmup manifest receives nothing; the moment its
``/readyz`` flips, traffic flows. In-flight requests on a replica
that dies mid-request fail (only those — no silent cross-replica
retry of possibly-executed work); requests never yet sent to a
replica are always safe to re-route.

``swap_weights`` is the rolling hot swap: one replica at a time is
drained (marked unroutable, outstanding waited to zero), told to
``/reload`` the version-stamped artifact (warm from the shared
compile cache), verified ready again, and returned to rotation —
zero downtime, zero failed in-flight requests, fleet-wide.
"""
from __future__ import annotations

import concurrent.futures
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ...observability import tracing
from ..generation.engine import StreamingFuture
from ..request import QueueFullError, ServerClosedError
from . import codec
from .metrics import FleetMetrics, merge_prometheus_texts

__all__ = ["FleetRouter", "RouterApp", "NoReadyReplicaError",
           "ReplicaError"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


# data-plane traffic is always direct to the replica sockets — an
# http_proxy env var must never detour (or break) intra-fleet calls
_OPENER = urllib.request.build_opener(
    urllib.request.ProxyHandler({}))


class NoReadyReplicaError(ServerClosedError):
    """No replica is currently ready to take traffic."""


class ReplicaError(RuntimeError):
    """A replica failed mid-request (connection died after dispatch);
    only the requests riding that connection fail."""


class _Replica:
    """Router-side view of one replica. Mutable fields are guarded by
    the router lock."""

    __slots__ = ("replica_id", "url", "outstanding", "ready", "alive",
                 "draining", "version", "errors")

    def __init__(self, replica_id, url: str):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        self.outstanding = 0
        self.ready = False
        self.alive = False
        self.draining = False
        self.version: Optional[str] = None
        self.errors = 0


class FleetRouter:
    """Load balancer + swap orchestrator over a replica set.

    ``replicas`` seeds a static ``{id: url}`` map; ``supervisor``
    (optional) is re-polled every health tick so spawned/respawned
    replicas join and dead ones leave automatically — when attached,
    the supervisor is authoritative for the replica set.
    ``start=False`` skips the poll thread (tests drive
    ``poll_replicas()`` explicitly)."""

    def __init__(self, replicas: Optional[Mapping] = None, *,
                 supervisor=None, retries: Optional[int] = None,
                 health_interval_ms: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 pool_size: Optional[int] = None,
                 name: str = "fleet", start: bool = True):
        self.name = name
        self.supervisor = supervisor
        self.retries = int(retries if retries is not None
                           else _flag("FLAGS_fleet_retries", 2))
        self.health_interval_ms = float(
            health_interval_ms if health_interval_ms is not None
            else _flag("FLAGS_fleet_health_interval_ms", 200.0))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else _flag("FLAGS_fleet_request_timeout_s", 120.0))
        self.metrics = FleetMetrics(name)
        # stamp this process's spans as the router's (only when nothing
        # else named the process — a worker main() names it first)
        if tracing.process_name().startswith("pid-"):
            tracing.set_process_name(f"router-{name}")
        self._lock = threading.Lock()
        self._replicas: Dict[object, _Replica] = {}
        self._rr = 0                    # round-robin tie-breaker
        self._closed = False
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_wake = threading.Event()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=int(pool_size) if pool_size else 32,
            thread_name_prefix=f"fleet-router-{name}")
        for rid, url in (replicas or {}).items():
            self._replicas[rid] = _Replica(rid, url)
        if supervisor is not None:
            self._sync_supervisor()
        self.poll_replicas()            # synchronous first probe
        if start:
            self._start_polling()

    # ------------------------------------------------------ replica set
    def add_replica(self, replica_id, url: str):
        with self._lock:
            if replica_id not in self._replicas:
                self._replicas[replica_id] = _Replica(replica_id, url)

    def remove_replica(self, replica_id):
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.metrics.drop_replica(str(replica_id))

    def _sync_supervisor(self):
        eps = self.supervisor.endpoints()
        with self._lock:
            for rid, url in eps.items():
                rep = self._replicas.get(rid)
                if rep is None:
                    self._replicas[rid] = _Replica(rid, url)
                elif rep.url != url.rstrip("/"):
                    # respawned under the same id: fresh state
                    self._replicas[rid] = _Replica(rid, url)
            for rid in list(self._replicas):
                if rid not in eps:
                    self._replicas.pop(rid)

    def _http(self, url: str, data: Optional[bytes] = None,
              timeout: Optional[float] = None,
              ctype: str = "application/octet-stream"):
        req = urllib.request.Request(
            url, data=data, method="POST" if data is not None
            else "GET")
        if data is not None:
            req.add_header("Content-Type", ctype)
        return _OPENER.open(req,
                            timeout=timeout or self.request_timeout_s)

    def poll_replicas(self):
        """One readiness sweep over the known set (plus a supervisor
        re-sync when attached). The poll thread calls this on its
        cadence; tests and ``wait_ready`` call it directly."""
        if self.supervisor is not None:
            self._sync_supervisor()
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            ready, alive, version = False, False, None
            try:
                with self._http(rep.url + "/readyz",
                                timeout=max(
                                    2.0, self.health_interval_ms
                                    / 1e3)) as resp:
                    body = json.loads(resp.read() or b"{}")
                    ready, alive = bool(body.get("ready")), True
                    version = body.get("version")
            except urllib.error.HTTPError as e:
                alive = True            # it answered: alive, not ready
                try:
                    version = json.loads(
                        e.read() or b"{}").get("version")
                except ValueError:
                    pass
            except Exception:  # noqa: BLE001 - unreachable = not live
                pass
            with self._lock:
                if self._replicas.get(rep.replica_id) is rep:
                    rep.ready, rep.alive = ready, alive
                    if version:
                        rep.version = version
        self._update_state_gauges()

    def _update_state_gauges(self):
        with self._lock:
            reps = list(self._replicas.values())
            known = len(reps)
            ready = sum(1 for r in reps
                        if r.ready and not r.draining)
            live = sum(1 for r in reps if r.alive)
            draining = sum(1 for r in reps if r.draining)
        self.metrics.set_replica_states(known, ready, live, draining)

    def _start_polling(self):
        if self._poll_thread is None or \
                not self._poll_thread.is_alive():
            self._poll_thread = threading.Thread(
                target=self._poll_loop,
                name=f"fleet-router-poll-{self.name}", daemon=True)
            self._poll_thread.start()

    def _poll_loop(self):
        while not self._closed:
            self._poll_wake.wait(self.health_interval_ms / 1e3)
            self._poll_wake.clear()
            if self._closed:
                return
            try:
                self.poll_replicas()
            except Exception:  # noqa: BLE001 - the poll loop must
                pass           # survive any replica weirdness

    def wait_ready(self, n: int = 1, timeout: float = 60.0) -> bool:
        """Block until >= n replicas are routable (ready, not
        draining)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll_replicas()
            if len(self._routable()) >= n:
                return True
            time.sleep(0.05)
        return len(self._routable()) >= n

    # ------------------------------------------------------ routing
    def _routable(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.ready and r.alive and not r.draining]

    def _pick(self, exclude: set) -> Optional[_Replica]:
        with self._lock:
            ready = [r for r in self._replicas.values()
                     if r.ready and r.alive and not r.draining
                     and r.replica_id not in exclude]
            if not ready:
                return None
            low = min(r.outstanding for r in ready)
            tied = [r for r in ready if r.outstanding == low]
            self._rr += 1
            return tied[self._rr % len(tied)]

    def _acquire(self, rep: _Replica, n: int):
        with self._lock:
            rep.outstanding += n
            out = rep.outstanding
        self.metrics.set_outstanding(str(rep.replica_id), out)

    def _release(self, rep: _Replica, n: int):
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - n)
            out = rep.outstanding
        self.metrics.set_outstanding(str(rep.replica_id), out)

    def _traced_forward(self, body: bytes, n_req: int,
                        timeout_ms: Optional[float],
                        ctx) -> bytes:
        """``_forward_batch`` under a ``router::request`` root span
        (no-op wrapper when untraced). Failure records an errored root
        span, which tail-promotes an unsampled trace."""
        if ctx is None:
            return self._forward_batch(body, n_req, timeout_ms)
        rctx = ctx.child()
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        attrs = {"router": self.name, "n_req": n_req}
        try:
            payload = self._forward_batch(body, n_req, timeout_ms,
                                          ctx=rctx)
        except BaseException as e:
            tracing.record_span(
                rctx, "router::request", stage="router",
                start_unix_ns=t_wall,
                duration_ms=(time.perf_counter() - t0) * 1e3,
                status="error",
                attrs=dict(attrs,
                           error=f"{type(e).__name__}: {e}"),
                root=True)
            raise
        tracing.record_span(
            rctx, "router::request", stage="router",
            start_unix_ns=t_wall,
            duration_ms=(time.perf_counter() - t0) * 1e3,
            attrs=attrs, root=True)
        return payload

    def _forward_batch(self, body: bytes, n_req: int,
                       timeout_ms: Optional[float],
                       ctx=None) -> bytes:
        """Send one encoded batch to the best replica, with the
        shed/unavailable retry policy. Returns the raw results
        payload (the HTTP front-end passes it through untouched; the
        Python API decodes it). With ``ctx``, every attempt gets a
        ``router::forward`` span and the batch is stamped with a
        trace trailer so the replica joins the trace."""
        self.metrics.count("routed", n_req)
        suffix = f"/submit_many?timeout_ms={timeout_ms}" \
            if timeout_ms else "/submit_many"
        attempts = 0
        tried: set = set()
        while True:
            rep = self._pick(tried)
            if rep is None and tried:
                # every routable replica tried: widen to re-tries
                tried = set()
                rep = self._pick(tried)
            if rep is None:
                self.metrics.count("shed", n_req)
                raise NoReadyReplicaError(
                    "no ready replica (fleet cold, draining, or "
                    "down)")
            self._acquire(rep, n_req)
            fctx = ctx.child() if ctx is not None else None
            send_body = codec.attach_trace_trailer(
                body, [fctx.to_traceparent()] * n_req) \
                if fctx is not None else body
            span_status, span_err = "ok", None
            t_wall = time.time_ns()
            t0 = time.perf_counter()
            try:
                with self._http(rep.url + suffix, data=send_body,
                                ctype="application/x-paddle-fleet"
                                ) as resp:
                    payload = resp.read()
                ms = (time.perf_counter() - t0) * 1e3
                self.metrics.observe_latency(ms)
                self.metrics.count("completed", n_req)
                if ctx is not None:
                    tracing.record_exemplar("paddle_fleet_request_ms",
                                            ms, ctx.trace_id)
                return payload
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 429:       # replica shed the whole batch
                    self.metrics.count_shed(str(rep.replica_id))
                    reason = "queue_full"
                elif e.code == 503:     # closed/not ready after all
                    with self._lock:
                        rep.ready = False
                    reason = "unavailable"
                else:
                    self.metrics.count("failed", n_req)
                    span_status, span_err = "error", f"HTTP {e.code}"
                    raise ReplicaError(
                        f"replica {rep.replica_id} returned HTTP "
                        f"{e.code}")
                span_status, span_err = "error", reason
            except (ConnectionRefusedError, urllib.error.URLError,
                    ConnectionResetError, TimeoutError) as e:
                # Refused before the request was read: nothing
                # executed, safe to re-route. Anything after dispatch
                # may have executed — fail, don't double-run.
                refused = isinstance(e, ConnectionRefusedError) or \
                    isinstance(getattr(e, "reason", None),
                               ConnectionRefusedError)
                with self._lock:
                    rep.alive = refused and rep.alive
                    rep.ready = False
                span_status = "error"
                span_err = f"{type(e).__name__}: {e}"
                if not refused:
                    self.metrics.count("failed", n_req)
                    raise ReplicaError(
                        f"replica {rep.replica_id} died mid-request: "
                        f"{type(e).__name__}: {e}") from e
                reason = "unavailable"
            finally:
                self._release(rep, n_req)
                if fctx is not None:
                    f_attrs = {"replica": str(rep.replica_id),
                               "attempt": attempts}
                    if span_err:
                        f_attrs["error"] = span_err
                    tracing.record_span(
                        fctx, "router::forward", stage="forward",
                        start_unix_ns=t_wall,
                        duration_ms=(time.perf_counter() - t0) * 1e3,
                        status=span_status, attrs=f_attrs, root=True)
            tried.add(rep.replica_id)
            attempts += 1
            if attempts > self.retries:
                self.metrics.count("shed", n_req)
                raise QueueFullError(
                    f"fleet shed the batch after {attempts} "
                    f"attempts (all replicas at capacity)")
            self.metrics.count_retry(reason)

    # ------------------------------------------------------ client API
    def submit(self, feed, timeout_ms: Optional[float] = None):
        """One request -> Future of its output-array list (the
        ``InferenceServer.submit`` contract, fleet-wide)."""
        return self.submit_many([feed], timeout_ms=timeout_ms)[0]

    def submit_many(self, feeds: Sequence,
                    timeout_ms: Optional[float] = None):
        """Bulk submit: the batch rides ONE replica dispatch (the
        replica's dynamic batcher coalesces it further). Returns one
        Future per request; per-request replica-side failures resolve
        individual futures, a fleet-wide shed fails them all with
        QueueFullError."""
        if self._closed:
            raise ServerClosedError("router is shut down")
        norm = []
        for f in feeds:
            if isinstance(f, dict):
                raise TypeError(
                    "fleet submit takes positional feed lists "
                    "(ordered like the model's inputs); dict feeds "
                    "are a single-process InferenceServer feature")
            norm.append([np.asarray(a) for a in f]
                        if isinstance(f, (list, tuple))
                        else [np.asarray(f)])
        body = codec.encode_batch(norm)
        futs = [concurrent.futures.Future() for _ in norm]
        # trace identity is captured on the CALLER's thread (ambient
        # context or a fresh sampled one); the whole batch rides one
        # trace — the single-request submit() case is the 1:1 trace
        # the /tracez recipe documents
        ctx = tracing.request_context()

        def _run():
            try:
                payload = self._traced_forward(body, len(norm),
                                               timeout_ms, ctx)
                results = codec.decode_results(payload)
                if len(results) != len(futs):
                    raise ReplicaError(
                        f"replica answered {len(results)} results "
                        f"for {len(futs)} requests")
            except BaseException as e:  # noqa: BLE001 - fail them all
                for f in futs:
                    if f.set_running_or_notify_cancel():
                        f.set_exception(e)
                return
            for f, res in zip(futs, results):
                if not f.set_running_or_notify_cancel():
                    continue
                if isinstance(res, BaseException):
                    f.set_exception(res)
                else:
                    f.set_result(res)

        self._pool.submit(_run)
        return futs

    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        temperature: float = 0.0,
                        timeout_ms: Optional[float] = None,
                        seed: Optional[int] = None) -> StreamingFuture:
        """Fleet-wide ``GenerationServer.submit_generate``: tokens
        stream back through the returned future as the chosen
        replica's decode loop emits them."""
        if self._closed:
            raise ServerClosedError("router is shut down")
        fut = StreamingFuture()
        ctx = tracing.request_context()
        gctx = ctx.child() if ctx is not None else None
        req = {
            "prompt": [int(t) for t in np.asarray(prompt).ravel()],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "timeout_ms": timeout_ms, "seed": seed}
        if gctx is not None:
            req["traceparent"] = gctx.to_traceparent()
        body = json.dumps(req).encode()
        self.metrics.count("routed")
        self._pool.submit(self._run_generate_traced, body, fut, gctx)
        return fut

    def _run_generate_traced(self, body: bytes, fut: StreamingFuture,
                             gctx=None):
        """``_run_generate`` under a ``router::generate`` root span
        whose status mirrors the stream's outcome."""
        if gctx is None:
            return self._run_generate(body, fut)
        t_wall = time.time_ns()
        t0 = time.perf_counter()
        self._run_generate(body, fut)
        exc = fut.exception()
        reason = fut.finish_reason
        attrs = {"router": self.name,
                 "finish_reason": reason or ""}
        if exc is not None:
            attrs["error"] = f"{type(exc).__name__}: {exc}"
        tracing.record_span(
            gctx, "router::generate", stage="router",
            start_unix_ns=t_wall,
            duration_ms=(time.perf_counter() - t0) * 1e3,
            status="error" if exc is not None else "ok",
            attrs=attrs, root=True)

    def _run_generate(self, body: bytes, fut: StreamingFuture):
        tried: set = set()
        for attempt in range(self.retries + 1):
            rep = self._pick(tried)
            if rep is None:
                tried = set()
                rep = self._pick(tried)
            if rep is None:
                self.metrics.count("shed")
                fut._fail(NoReadyReplicaError("no ready replica"),
                          reason="shed")
                return
            self._acquire(rep, 1)
            emitted = False
            try:
                with self._http(rep.url + "/generate", data=body,
                                ctype="application/json") as resp:
                    for line in resp:
                        if fut._cancel_requested:
                            fut._finish("cancelled")
                            return
                        ev = json.loads(line)
                        if ev.get("done"):
                            reason = ev.get("finish_reason", "eos")
                            if ev.get("error"):
                                fut._fail(
                                    ReplicaError(ev["error"]),
                                    reason="error")
                            else:
                                fut._finish(reason)
                            self.metrics.count("completed")
                            return
                        emitted = True
                        fut._emit(int(ev["t"]))
                # stream closed without a terminal event: the replica
                # died mid-stream
                raise ReplicaError(
                    f"replica {rep.replica_id} closed the stream "
                    f"mid-generation")
            except urllib.error.HTTPError as e:
                e.read()
                if e.code in (429, 503) and not emitted:
                    self.metrics.count_retry(
                        "queue_full" if e.code == 429
                        else "unavailable")
                    if e.code == 429:
                        self.metrics.count_shed(str(rep.replica_id))
                    tried.add(rep.replica_id)
                    continue
                self.metrics.count("failed")
                fut._fail(QueueFullError(f"HTTP {e.code}")
                          if e.code == 429
                          else ReplicaError(f"HTTP {e.code}"),
                          reason="error")
                return
            except BaseException as e:  # noqa: BLE001 - tokens may
                # already be consumed: never silently re-run the
                # stream on another replica
                if not emitted and isinstance(
                        e, (ConnectionRefusedError,
                            urllib.error.URLError)):
                    with self._lock:
                        rep.ready = False
                    self.metrics.count_retry("unavailable")
                    tried.add(rep.replica_id)
                    continue
                self.metrics.count("failed")
                fut._fail(ReplicaError(
                    f"replica {rep.replica_id} stream failed: "
                    f"{type(e).__name__}: {e}"), reason="error")
                return
            finally:
                self._release(rep, 1)
        self.metrics.count("shed")
        fut._fail(QueueFullError(
            f"fleet shed the stream after {self.retries + 1} "
            f"attempts"), reason="shed")

    # ------------------------------------------------------ hot swap
    def swap_weights(self, model_prefix: str, *,
                     drain_timeout_s: Optional[float] = None,
                     ready_timeout_s: float = 300.0) -> dict:
        """Rolling hot weight swap: drain -> /reload -> ready, one
        replica at a time. Raises on the first failed replica (the
        already-swapped ones keep the new weights, the rest keep the
        old — the fleet stays serviceable either way); the drained
        replica is always returned to rotation."""
        drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _flag("FLAGS_fleet_drain_timeout_s", 30.0))
        report = {"model_prefix": str(model_prefix), "replicas": []}
        with self._lock:
            order = sorted(self._replicas,
                           key=lambda rid: str(rid))
        for rid in order:
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or not rep.alive:
                    continue
                rep.draining = True
            self._update_state_gauges()
            t0 = time.perf_counter()
            try:
                self._drain_one(rep, drain_timeout_s)
                t_drained = time.perf_counter()
                with self._http(
                        rep.url + "/reload",
                        data=json.dumps(
                            {"model_prefix": str(model_prefix)}
                        ).encode(),
                        ctype="application/json",
                        timeout=ready_timeout_s) as resp:
                    version = json.loads(resp.read()).get("version")
                self._await_ready(rep, ready_timeout_s)
                self.metrics.count_swap("replica_reloaded")
                report["replicas"].append({
                    "replica": str(rid), "version": version,
                    "drain_ms": round((t_drained - t0) * 1e3, 1),
                    "reload_ms": round(
                        (time.perf_counter() - t_drained) * 1e3, 1)})
            except BaseException:
                self.metrics.count_swap("failed")
                raise
            finally:
                with self._lock:
                    rep.draining = False
                self._update_state_gauges()
        self.metrics.count_swap("completed")
        return report

    def _drain_one(self, rep: _Replica, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if rep.outstanding == 0:
                    return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {rep.replica_id} still has "
                    f"{rep.outstanding} outstanding after "
                    f"{timeout_s}s drain")
            time.sleep(0.002)

    def _await_ready(self, rep: _Replica, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with self._http(rep.url + "/readyz",
                                timeout=5.0) as resp:
                    if json.loads(resp.read()).get("ready"):
                        with self._lock:
                            rep.ready, rep.alive = True, True
                        return
            except Exception:  # noqa: BLE001 - keep polling until the
                pass           # deadline decides
            time.sleep(0.02)
        raise TimeoutError(
            f"replica {rep.replica_id} not ready again within "
            f"{timeout_s}s of reload")

    # ------------------------------------------------------ inspection
    def replica_states(self) -> List[dict]:
        with self._lock:
            return [{"replica": str(r.replica_id), "url": r.url,
                     "ready": r.ready, "alive": r.alive,
                     "draining": r.draining,
                     "outstanding": r.outstanding,
                     "version": r.version}
                    for r in self._replicas.values()]

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def merged_metrics(self) -> str:
        """Fleet-wide Prometheus scrape: this process's registry plus
        every live replica's /metrics re-labeled with its id."""
        from ...observability import default_registry, prometheus_text
        texts = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/metrics",
                                timeout=5.0) as resp:
                    texts[rid] = resp.read().decode()
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # just drops out of the merged view
        return merge_prometheus_texts(
            texts, own=prometheus_text(default_registry()))

    def merged_tracez(self, trace_id: Optional[str] = None,
                      min_duration_ms: Optional[float] = None,
                      limit: int = 100) -> dict:
        """Fleet-wide ``/tracez``: this process's flight recorder plus
        every live replica's, stitched by trace id — the router span,
        the worker span, and the engine's queue/assembly/dispatch/
        device/fetch children come back as ONE trace."""
        remote: List[dict] = []
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        q = f"?limit={int(limit)}"
        if trace_id:
            q += f"&trace_id={trace_id}"
        for rid, url in reps:
            try:
                with self._http(url + "/tracez" + q,
                                timeout=5.0) as resp:
                    doc = json.loads(resp.read())
                for t in doc.get("traces", []):
                    remote.extend(t.get("spans", []))
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        return tracing.tracez_payload(
            trace_id=trace_id, min_duration_ms=min_duration_ms,
            limit=limit, extra_spans=remote)

    def merged_sloz(self) -> dict:
        """Fleet-wide ``/sloz``: this process's SLO evaluation plus
        every live replica's, with rolling-window good/total counts
        summed per (SLO, window) — fleet attainment, the way
        ``merged_tracez`` stitches spans."""
        from ...observability import slo as slo_mod
        own = slo_mod.sloz_payload()
        remotes: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/sloz", timeout=5.0) as resp:
                    remotes[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        return slo_mod.merge_sloz_payloads(own, remotes)

    def merged_execz(self) -> dict:
        """Fleet-wide ``/execz``: this process's executable registry
        plus every live replica's, keyed by replica id, with a
        fleet-level per-site rollup — which replica is running which
        executables at what cost, one page."""
        from ...observability import xstats
        own = xstats.execz_payload()
        replicas: Dict[str, dict] = {}
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        for rid, url in reps:
            try:
                with self._http(url + "/execz", timeout=10.0) as resp:
                    replicas[rid] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 - a scrape-dead replica
                pass           # drops out of the merged view
        fleet_sites: Dict[str, dict] = {}
        for payload in replicas.values():
            for site, s in (payload.get("sites") or {}).items():
                agg = fleet_sites.setdefault(
                    site, {"entries": 0, "dispatches": 0, "flops": 0.0})
                agg["entries"] += s.get("entries", 0)
                agg["dispatches"] += s.get("dispatches", 0)
                agg["flops"] = max(agg["flops"], s.get("flops", 0.0))
        return {"router": own, "replicas": replicas,
                "fleet": {"sites": fleet_sites,
                          "replicas_merged": len(replicas)}}

    def merged_profilez(self, duration_ms: Optional[float] = None
                        ) -> dict:
        """Fleet-wide ``/profilez``: without a duration, every live
        replica's capture ring; with one, fan a bounded capture out to
        ALL live replicas concurrently and return the stitched bundle
        of chrome-trace documents keyed by replica id."""
        with self._lock:
            reps = [(str(r.replica_id), r.url)
                    for r in self._replicas.values() if r.alive]
        q = f"?duration_ms={float(duration_ms)}" if duration_ms else ""

        def one(url):
            timeout = 10.0 + (float(duration_ms) / 1e3
                              if duration_ms else 0.0)
            with self._http(url + "/profilez" + q,
                            timeout=timeout) as resp:
                return json.loads(resp.read())

        replicas: Dict[str, dict] = {}
        futs = {rid: self._pool.submit(one, url) for rid, url in reps}
        for rid, fut in futs.items():
            try:
                replicas[rid] = fut.result()
            except Exception as e:  # noqa: BLE001 - a refused or dead
                replicas[rid] = {"error": repr(e)}  # replica is still
                # part of the bundle: the operator sees who failed
        return {"replicas": replicas,
                "captured": duration_ms is not None,
                "replicas_merged": len(replicas)}

    def statusz(self) -> dict:
        """Fleet status page: per-replica id/readiness/outstanding/
        version (+ restart counts when a supervisor is attached) and
        the router's own counters — the single-server ``/statusz``
        parity view for a fleet."""
        replicas = self.replica_states()
        restarts = {}
        if self.supervisor is not None:
            try:
                restarts = {str(k): v for k, v in
                            self.supervisor.restart_counts().items()}
            except Exception:  # noqa: BLE001 - status must not 500 on
                pass           # a half-stopped supervisor
        for r in replicas:
            r["restarts"] = restarts.get(r["replica"], 0)
        return {
            "router": self.name,
            "pid": os.getpid(),
            "replicas": replicas,
            "ready_replicas": sum(1 for r in replicas
                                  if r["ready"] and not r["draining"]),
            "restarts_total": sum(restarts.values()),
            "metrics": self.metrics_snapshot(),
        }

    # ------------------------------------------------------ lifecycle
    def shutdown(self):
        self._closed = True
        self._poll_wake.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


# ---------------------------------------------------------------- http
class _RouterHandler(BaseHTTPRequestHandler):
    """HTTP front-end over a FleetRouter: the external data plane.

    ``POST /submit_many`` and ``POST /generate`` speak the replica
    wire protocol (codec.py / ndjson) and PASS THE BODY THROUGH — the
    router never decodes the arrays, so its per-request CPU cost is a
    replica pick plus a socket copy. Serving-layer errors map to the
    same status codes replicas use (429 shed, 503 no ready replica),
    so a client cannot tell one server from a fleet."""

    server_version = "paddle-tpu-fleet-router/1.0"

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @property
    def _router(self) -> FleetRouter:
        return self.server.router  # type: ignore[attr-defined]

    def log_message(self, *args):
        pass

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                from ...observability import (default_registry,
                                              prometheus_text)
                from ...observability.exposition import \
                    PROMETHEUS_CONTENT_TYPE
                text = self._router.merged_metrics() \
                    if "merged=1" in query \
                    else prometheus_text(default_registry())
                self._send(200, text.encode(),
                           PROMETHEUS_CONTENT_TYPE)
            elif path == "/healthz":
                live = any(s["alive"]
                           for s in self._router.replica_states())
                self._send(200 if live else 503, json.dumps(
                    {"ok": live,
                     "replicas": self._router.replica_states()},
                    sort_keys=True).encode())
            elif path == "/readyz":
                n = len(self._router._routable())
                self._send(200 if n else 503, json.dumps(
                    {"ready": bool(n),
                     "ready_replicas": n}).encode())
            elif path == "/statusz":
                self._send(200, json.dumps(
                    self._router.statusz(),
                    sort_keys=True, default=str).encode())
            elif path == "/tracez":
                from urllib.parse import parse_qs
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                doc = self._router.merged_tracez(
                    trace_id=q.get("trace_id") or None,
                    min_duration_ms=float(q["min_ms"])
                    if q.get("min_ms") else None,
                    limit=int(q.get("limit", 100)))
                if q.get("format") == "chrome":
                    from ...observability import tracing as _tracing
                    spans = [s for t in doc["traces"]
                             for s in t["spans"]]
                    doc = {"traceEvents":
                           _tracing.chrome_trace_events(spans)}
                self._send(200, json.dumps(doc, sort_keys=True,
                                           default=str).encode())
            elif path == "/sloz":
                self._send(200, json.dumps(
                    self._router.merged_sloz(), sort_keys=True,
                    default=str).encode())
            elif path == "/goodputz":
                from ...observability.goodput import goodputz_payload
                self._send(200, json.dumps(
                    goodputz_payload(), sort_keys=True).encode())
            elif path == "/execz":
                self._send(200, json.dumps(
                    self._router.merged_execz(), sort_keys=True,
                    default=str).encode())
            elif path == "/profilez":
                from urllib.parse import parse_qs
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                doc = self._router.merged_profilez(
                    duration_ms=float(q["duration_ms"])
                    if q.get("duration_ms") else None)
                self._send(200, json.dumps(doc, sort_keys=True,
                                           default=str).encode())
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as e:  # noqa: BLE001 - handler fault barrier
            try:
                self._send(500, f"{e!r}\n".encode(), "text/plain")
            except Exception:  # noqa: BLE001
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else b""
            if path == "/submit_many":
                timeout_ms = None
                for part in query.split("&"):
                    if part.startswith("timeout_ms="):
                        timeout_ms = \
                            float(part.split("=", 1)[1]) or None
                n_req = codec.peek_batch_size(body)
                # external ingress: honor the caller's traceparent
                # header, else make the head-sampling decision here
                ctx = tracing.parse_traceparent(
                    self.headers.get("traceparent")) or \
                    tracing.request_context()
                payload = self._router._traced_forward(
                    body, n_req, timeout_ms, ctx)
                self._send(200, payload, "application/x-paddle-fleet")
            elif path == "/generate":
                self._generate(body)
            else:
                self._send(404, b"not found\n", "text/plain")
        except NoReadyReplicaError as e:
            self._send(503, f"{e}\n".encode(), "text/plain")
        except QueueFullError as e:
            self._send(429, f"{e}\n".encode(), "text/plain")
        except codec.CodecError as e:
            self._send(400, f"{e}\n".encode(), "text/plain")
        except Exception as e:  # noqa: BLE001 - handler fault barrier
            try:
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain")
            except Exception:  # noqa: BLE001
                pass

    def _generate(self, body: bytes):
        req = json.loads(body or b"{}")
        ctx = tracing.parse_traceparent(
            req.get("traceparent")
            or self.headers.get("traceparent"))
        with tracing.use_context(ctx):
            fut = self._router.submit_generate(
                req["prompt"],
                max_new_tokens=int(req.get("max_new_tokens", 32)),
                temperature=float(req.get("temperature", 0.0)),
                timeout_ms=req.get("timeout_ms"),
                seed=req.get("seed"))
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        try:
            for tok in fut:
                self.wfile.write(
                    json.dumps({"t": int(tok)}).encode() + b"\n")
                self.wfile.flush()
            self.wfile.write(json.dumps(
                {"done": True,
                 "finish_reason": fut.finish_reason}).encode() + b"\n")
        except BrokenPipeError:
            fut.cancel()
        except BaseException as e:  # noqa: BLE001 - stream the error
            try:
                self.wfile.write(json.dumps(
                    {"done": True, "finish_reason": "error",
                     "error": f"{type(e).__name__}: {e}"}).encode()
                    + b"\n")
            except OSError:
                pass


class RouterApp:
    """The router's HTTP front-end on a daemon thread (same shape as
    worker.ReplicaApp). ``port=0`` binds ephemeral."""

    def __init__(self, router: FleetRouter, host: str = "0.0.0.0",
                 port: int = 0):
        self.router = router
        self.host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path: str = "") -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "::") \
            else self.host
        return f"http://{host}:{self.port}{path}"

    def start(self) -> "RouterApp":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _RouterHandler)
        httpd.daemon_threads = True
        httpd.router = self.router      # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"fleet-router-http-{self.router.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
