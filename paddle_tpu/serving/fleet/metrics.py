"""Router-side fleet metrics + the merged multi-replica /metrics view.

``FleetMetrics`` puts the router's counters on the PR 3 registry as
``paddle_fleet_*`` families, so the router process's own telemetry
endpoint exposes them alongside everything else:

- ``paddle_fleet_requests_total{router,event}`` — routed / completed /
  failed / shed request counts (shed = rejected after the retry budget)
- ``paddle_fleet_retries_total{router,reason}`` — re-dispatches after a
  replica shed (queue_full) or refused/unreachable dispatch
  (unavailable)
- ``paddle_fleet_sheds_total{router,replica}`` — per-replica 429s seen
- ``paddle_fleet_outstanding{router,replica}`` — in-flight requests per
  replica (the least-outstanding routing signal, exported)
- ``paddle_fleet_replicas{router,state}`` — ready / live / draining /
  known replica counts
- ``paddle_fleet_replica_restarts_total{fleet}`` — supervisor respawns
- ``paddle_fleet_swaps_total{router,event}`` — rolling weight-swap
  lifecycle (replica_reloaded / completed / failed)
- ``paddle_fleet_request_ms{router}`` — router-observed end-to-end
  batch latency
- ``paddle_fleet_breaker_transitions_total{router,replica,state}`` —
  circuit-breaker state entries (open / half_open / closed) per
  replica
- ``paddle_fleet_breaker_open{router,replica}`` — 1 while the
  replica's breaker is open or half-open (shedding), else 0
- ``paddle_fleet_hedges_total{router,event}`` — hedged-request
  accounting: fired (a hedge dispatched), won (the hedge answered
  first), wasted (the loser completed anyway — duplicate execution
  paid for nothing)
- ``paddle_fleet_deadline_rejects_total{router,where}`` — requests
  rejected on an exhausted deadline budget, by the hop that caught
  it (router / worker)

``merge_prometheus_texts`` builds the fleet-wide scrape: each
replica's own /metrics text re-labeled with ``replica="<id>"`` and
concatenated under de-duplicated HELP/TYPE headers, so one scrape of
the router shows every replica's serving counters without a discovery
config.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["FleetMetrics", "merge_prometheus_texts"]

_EVENTS = ("routed", "completed", "failed", "shed")
_SWAP_EVENTS = ("replica_reloaded", "completed", "failed")
_HEDGE_EVENTS = ("fired", "won", "wasted")


class FleetMetrics:
    """Typed fleet metric families plus a JSON snapshot (the BENCH
    record format). All families live on the default registry."""

    def __init__(self, name: str, window: int = 4096, registry=None):
        from ...observability.registry import (PercentileWindow,
                                               default_registry)
        self.name = name
        self._lock = threading.Lock()
        reg = registry or default_registry()
        self._f_events = reg.counter(
            "paddle_fleet_requests_total",
            "router request lifecycle events", ("router", "event"))
        self._f_retries = reg.counter(
            "paddle_fleet_retries_total",
            "batch re-dispatches after a replica shed or refused",
            ("router", "reason"))
        self._f_sheds = reg.counter(
            "paddle_fleet_sheds_total",
            "QueueFullError (HTTP 429) sheds observed per replica",
            ("router", "replica"))
        self._f_outstanding = reg.gauge(
            "paddle_fleet_outstanding",
            "in-flight requests per replica (least-outstanding "
            "routing signal)", ("router", "replica"))
        self._f_replicas = reg.gauge(
            "paddle_fleet_replicas",
            "replica counts by state", ("router", "state"))
        self._f_restarts = reg.counter(
            "paddle_fleet_replica_restarts_total",
            "replica processes respawned by the supervisor after an "
            "unexpected exit", ("fleet",))
        self._f_swaps = reg.counter(
            "paddle_fleet_swaps_total",
            "rolling weight-swap lifecycle events", ("router", "event"))
        self._f_lat = reg.histogram(
            "paddle_fleet_request_ms",
            "router-observed end-to-end batch latency", ("router",))
        self._f_breaker = reg.counter(
            "paddle_fleet_breaker_transitions_total",
            "circuit-breaker state entries per replica",
            ("router", "replica", "state"))
        self._f_breaker_open = reg.gauge(
            "paddle_fleet_breaker_open",
            "1 while the replica's breaker sheds (open/half-open)",
            ("router", "replica"))
        self._f_hedges = reg.counter(
            "paddle_fleet_hedges_total",
            "hedged-request accounting (fired / won / wasted "
            "duplicate execution)", ("router", "event"))
        self._f_deadline = reg.counter(
            "paddle_fleet_deadline_rejects_total",
            "requests rejected on an exhausted deadline budget, by "
            "the hop that caught it", ("router", "where"))
        for fam in (self._f_events, self._f_retries, self._f_sheds,
                    self._f_outstanding, self._f_replicas,
                    self._f_swaps, self._f_lat, self._f_breaker,
                    self._f_breaker_open, self._f_hedges,
                    self._f_deadline):
            fam.clear(router=name)
        self._events = {e: self._f_events.labels(router=name, event=e)
                        for e in _EVENTS}
        self._retries = {r: self._f_retries.labels(router=name,
                                                   reason=r)
                         for r in ("queue_full", "unavailable")}
        self._swaps = {e: self._f_swaps.labels(router=name, event=e)
                       for e in _SWAP_EVENTS}
        self._states = {s: self._f_replicas.labels(router=name,
                                                   state=s)
                        for s in ("known", "ready", "live",
                                  "draining")}
        self._h_lat = self._f_lat.labels(router=name)
        self._hedges = {e: self._f_hedges.labels(router=name, event=e)
                        for e in _HEDGE_EVENTS}
        self._deadline = {w: self._f_deadline.labels(router=name,
                                                     where=w)
                          for w in ("router", "worker")}
        self._w_lat = PercentileWindow(int(window))

    def count(self, event: str, n: int = 1):
        self._events[event].inc(n)

    def count_retry(self, reason: str):
        self._retries[reason].inc()

    def count_shed(self, replica: str):
        self._f_sheds.labels(router=self.name, replica=replica).inc()

    def count_hedge(self, event: str, n: int = 1):
        self._hedges[event].inc(n)

    def count_deadline_reject(self, where: str, n: int = 1):
        self._deadline[where].inc(n)

    def count_breaker_transition(self, replica: str, state: str):
        self._f_breaker.labels(router=self.name, replica=replica,
                               state=state).inc()
        self._f_breaker_open.labels(
            router=self.name, replica=replica).set(
            0 if state == "closed" else 1)

    def count_restart(self):
        self._f_restarts.labels(fleet=self.name).inc()

    def count_swap(self, event: str):
        self._swaps[event].inc()

    def set_outstanding(self, replica: str, n: int):
        self._f_outstanding.labels(router=self.name,
                                   replica=replica).set(n)

    def drop_replica(self, replica: str):
        self._f_outstanding.clear(router=self.name, replica=replica)
        self._f_sheds.clear(router=self.name, replica=replica)
        self._f_breaker.clear(router=self.name, replica=replica)
        self._f_breaker_open.clear(router=self.name, replica=replica)

    def set_replica_states(self, known: int, ready: int, live: int,
                           draining: int):
        self._states["known"].set(known)
        self._states["ready"].set(ready)
        self._states["live"].set(live)
        self._states["draining"].set(draining)

    def observe_latency(self, ms: float):
        with self._lock:
            self._w_lat.observe(float(ms))
        self._h_lat.observe(float(ms))

    def snapshot(self) -> dict:
        with self._lock:
            lat = self._w_lat.snapshot()
        return {
            "router": self.name,
            "counters": {e: int(c.value)
                         for e, c in self._events.items()},
            "retries": {r: int(c.value)
                        for r, c in self._retries.items()},
            "swaps": {e: int(c.value)
                      for e, c in self._swaps.items()},
            "replicas": {s: int(g.value)
                         for s, g in self._states.items()},
            "restarts": int(
                self._f_restarts.labels(fleet=self.name).value),
            "hedges": {e: int(c.value)
                       for e, c in self._hedges.items()},
            "deadline_rejects": {w: int(c.value)
                                 for w, c in self._deadline.items()},
            "request_ms": lat,
        }


def merge_prometheus_texts(texts: Dict[str, str],
                           own: Optional[str] = None) -> str:
    """Merge per-replica Prometheus exposition texts into one scrape:
    every sample line gains a ``replica="<id>"`` label, and repeated
    ``# HELP`` / ``# TYPE`` headers (each replica declares the same
    families) are kept once. ``own`` (the router's local exposition)
    is prepended untouched."""
    out: List[str] = []
    seen_headers = set()
    if own:
        out.append(own.rstrip("\n"))
        for line in own.splitlines():
            if line.startswith("#"):
                seen_headers.add(line)
    for replica_id, text in sorted(texts.items()):
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                if line not in seen_headers:
                    seen_headers.add(line)
                    out.append(line)
                continue
            # sample line: name{labels} value  |  name value
            name, _, rest = line.partition(" ")
            if "{" in name:
                head, _, tail = name.partition("{")
                labeled = f'{head}{{replica="{replica_id}",{tail}'
            else:
                labeled = f'{name}{{replica="{replica_id}"}}'
            out.append(f"{labeled} {rest}" if rest else labeled)
    return "\n".join(out) + "\n"
