"""Replica worker: one process hosting a serving backend behind HTTP.

A fleet replica is this module run as a process
(``python -m paddle_tpu.serving.fleet.worker``): it builds a backend —
a real ``InferenceServer`` over a loaded ``Predictor`` (and optionally
a ``GenerationServer``), or the accelerator-emulating ``StubBackend``
— binds ``ReplicaApp`` (the stdlib HTTP service over that backend),
announces its port to the supervisor through an atomically-written
announce file, runs warmup (flipping readiness), and serves until
``POST /shutdown`` or SIGTERM.

Data plane (binary codec, see codec.py):

- ``POST /submit_many?timeout_ms=`` — one coalesced request batch in,
  per-request results out; whole-batch ``QueueFullError`` is HTTP 429
  (the router's shed/retry signal), per-request failures ride the
  results framing so one bad request never fails its batch peers.
- ``POST /generate`` — JSON request in, newline-delimited JSON token
  events streamed out (close-delimited body), one decode stream per
  connection.

Control plane (JSON):

- ``GET /healthz`` (liveness) / ``GET /readyz`` (readiness = warmup
  complete) / ``GET /metrics`` (this process's registry, Prometheus
  text) / ``GET /statusz`` / ``GET /tracez`` (this process's span
  flight recorder; the router's merged ``/tracez`` fans out to it) /
  ``GET /sloz`` (this process's SLO evaluation; the router's merged
  ``/sloz`` sums it fleet-wide) / ``GET /schedz`` (this process's
  admission + autoscaler state; the router's merged ``/schedz`` sums
  tenant shed counts fleet-wide) / ``GET /goodputz`` /
  ``GET /execz`` (this replica's executable cost/roofline registry;
  the router's ``/execz`` aggregates) / ``GET /profilez`` (capture
  ring; ``?duration_ms=`` runs one bounded device-profile capture)
- ``POST /reload`` — hot weight swap: load the version-stamped
  artifact named in the body, warm the replacement server from the
  shared compile cache + manifest, atomically swap it in, drain the
  old one. The router drains this replica first, so in-flight
  requests never see the swap.
- ``POST /shutdown`` — graceful exit.

Resilience (resilience.py, PR 15): the worker consumes the codec
DEADLINE trailer — a request whose budget is already exhausted when
the batch arrives is answered with ``DeadlineExceededError`` WITHOUT
ever being dispatched to the device — and hosts the DEVICE-WEDGE
WATCHDOG: backends bracket device work on a ``WedgeMonitor``; a
dispatch in flight longer than ``FLAGS_fleet_wedge_timeout_ms`` flips
``/readyz`` to not-ready, fails requests waiting on the device with
the typed ``ReplicaWedgedError``, and requests shutdown so the
supervisor's respawn (a warm start) replaces the wedged process — a
silent hang becomes a bounded, observable failure.

``ThreadReplicaFactory`` runs the same app+backend on a thread in the
current process — the tier-1 test double and the single-process
deployment mode; the wire protocol and routing logic are identical.
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...observability import tracing
from ..request import (DeadlineExceededError, QueueFullError,
                       QuotaExceededError, ServerClosedError)
from . import codec
from .resilience import ReplicaWedgedError, WedgeMonitor, WedgeWatchdog

__all__ = ["ReplicaApp", "PredictorBackend", "StubBackend",
           "ThreadReplicaFactory", "write_announce_file",
           "read_announce_file", "arm_wedge_watchdog", "arm_canary"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class _ConnectionDrop(Exception):
    """Raised by a backend to simulate a replica crash from the
    peer's perspective: the handler closes the connection without a
    response (the router sees a dead socket, exactly like a killed
    process) and the backend reports unhealthy afterwards."""


def write_announce_file(path: str, port: int):
    """Atomically publish this worker's address for the supervisor
    (partial reads are impossible: tmp + rename)."""
    data = json.dumps({"pid": os.getpid(), "port": int(port),
                       "url": f"http://127.0.0.1:{int(port)}"})
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


def read_announce_file(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class _WorkerMetrics:
    """Worker-process-side resilience counters on the default
    registry (the router's merged /metrics re-labels them with
    ``replica="<id>"``)."""

    def __init__(self):
        from ...observability.registry import default_registry
        reg = default_registry()
        name = tracing.process_name()
        self._deadline = reg.counter(
            "paddle_fleet_worker_deadline_rejects_total",
            "requests answered DeadlineExceededError at the worker "
            "without device dispatch (budget exhausted on arrival)",
            ("replica",)).labels(replica=name)
        self._wedges = reg.counter(
            "paddle_fleet_wedge_events_total",
            "device-wedge watchdog firings (dispatch exceeded "
            "FLAGS_fleet_wedge_timeout_ms)",
            ("replica",)).labels(replica=name)
        self._wedged = reg.gauge(
            "paddle_fleet_wedged",
            "1 after the watchdog declared this replica's device "
            "wedged (readiness stays red until restart)",
            ("replica",)).labels(replica=name)

    def count_deadline_reject(self, n: int = 1):
        self._deadline.inc(n)

    def count_wedge(self):
        self._wedges.inc()
        self._wedged.set(1)


_WM_LOCK = threading.Lock()
_WM: Optional[_WorkerMetrics] = None


def _worker_metrics() -> _WorkerMetrics:
    global _WM
    with _WM_LOCK:
        if _WM is None:
            _WM = _WorkerMetrics()
        return _WM


_WSCHED_LOCK = threading.Lock()
_WSCHED = None


def _worker_scheduler():
    """This worker process's admission controller (lazy singleton,
    registered on /schedz). Gates /submit_many per-tenant at cost 1
    token/request — the generation path gates in the engine instead,
    at prompt+max_new token cost. With the default policy (rate 0 =
    unlimited) the gate admits everything, so untagged fleets behave
    exactly as before."""
    global _WSCHED
    with _WSCHED_LOCK:
        if _WSCHED is None:
            from ..scheduling import AdmissionController
            from ..scheduling.schedz import register_controller
            _WSCHED = AdmissionController(
                name=f"worker:{tracing.process_name()}")
            register_controller(_WSCHED)
        return _WSCHED


def arm_wedge_watchdog(backend, app: "ReplicaApp", *,
                       timeout_ms: Optional[float] = None,
                       restart: bool = True,
                       name: Optional[str] = None
                       ) -> Optional[WedgeWatchdog]:
    """Attach the device-wedge watchdog to a backend exposing a
    ``wedge_monitor``: on firing it (1) marks the backend wedged so
    ``/readyz`` flips not-ready and device-lock waiters fail with
    ``ReplicaWedgedError``, (2) counts the event, and (3) with
    ``restart``, requests app shutdown so the worker process exits
    and the supervisor's respawn (a warm start) replaces it. Returns
    None when the backend has no monitor or the timeout disables the
    watchdog."""
    monitor = getattr(backend, "wedge_monitor", None)
    if monitor is None:
        return None

    def _on_wedge():
        _worker_metrics().count_wedge()
        mark = getattr(backend, "mark_wedged", None)
        if mark is not None:
            mark()
        if restart:
            app._request_shutdown()

    wd = WedgeWatchdog(
        monitor, timeout_ms=timeout_ms, on_wedge=_on_wedge,
        name=name or tracing.process_name())
    if not wd.enabled:
        return None
    app.watchdog = wd
    return wd.start()


def arm_canary(backend, app: "ReplicaApp", *,
               period_s: Optional[float] = None,
               name: Optional[str] = None,
               restart: bool = False):
    """Attach the numerics SDC canary to this replica: a deterministic
    checksum sweep per ``FLAGS_numerics_canary_period_s`` (and on
    not-ready→ready transitions). Backends exposing ``canary_probe``
    (the stub's corruption self-check) replace the generic device
    sweep with it. On a corruption episode the replica quarantines
    itself through the SAME path the wedge watchdog uses: readiness
    flips red (``/readyz`` reports ``corrupt``), so the router's
    poller opens the replica's breaker and drains it; with
    ``restart``, the worker also exits for a supervisor respawn.
    Returns the started runner or None when the period disables it."""
    from ...observability.numerics import CanaryRunner
    if period_s is None:
        period_s = float(
            _flag("FLAGS_numerics_canary_period_s", 0.0) or 0.0)
    probe = getattr(backend, "canary_probe", None)

    def _on_corrupt():
        mark = getattr(backend, "mark_corrupt", None)
        if mark is not None:
            mark()
        if restart:
            app._request_shutdown()

    runner = CanaryRunner(
        name=name or tracing.process_name(), period_s=period_s,
        probe=probe, ready_fn=backend.ready, on_corrupt=_on_corrupt)
    app.canary = runner
    return runner.start()


# ---------------------------------------------------------------- backends
class PredictorBackend:
    """The real replica backend: a ``Predictor`` loaded from a
    version-stamped artifact prefix, served by an ``InferenceServer``
    with the readiness gate on, optionally alongside a
    ``GenerationServer`` for decode traffic.

    ``reload(prefix)`` is the hot-swap path: build + warm a complete
    replacement server (compile-cache warm, so seconds not minutes),
    swap it in atomically, then drain the old one — callers queued on
    the old server finish on the old weights, everything after the
    swap runs the new ones.
    """

    def __init__(self, model_prefix: str, *,
                 max_batch_size: Optional[int] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1,
                 warmup_mode: str = "auto",
                 name: str = "replica",
                 generation_model=None):
        self._name = name
        self._max_batch_size = max_batch_size
        self._seq_buckets = list(seq_buckets) if seq_buckets else None
        self._seq_axis = int(seq_axis)
        self._warmup_mode = warmup_mode
        self._lock = threading.Lock()
        self._reloading = False
        self._gen = None
        self.wedge_monitor = WedgeMonitor()
        # replica = mesh: ONE backend owns every device of its
        # FLAGS_serving_mesh_mp tensor-parallel mesh (serving/mesh.py);
        # the router, codec, breakers, deadlines, tenant scheduling and
        # numerics canaries above this line see one replica as before.
        # Built once here so predictor AND generation engine (and every
        # reload) share the same device set.
        from ..mesh import serving_mesh_from_flags
        self.serving_mesh = serving_mesh_from_flags()
        self._server, self._version = self._build(model_prefix)
        if generation_model is not None:
            from ..generation import GenerationServer
            # share the worker's admission controller: the engine
            # gates at token cost and schedules decode WFQ/priority
            self._gen = GenerationServer(generation_model,
                                         name=f"{name}-gen",
                                         scheduler=_worker_scheduler(),
                                         mesh=self.serving_mesh)

    def _build(self, model_prefix: str):
        from ... import inference
        from ..server import InferenceServer
        pred = inference.create_predictor(
            inference.Config(str(model_prefix)))
        if self.serving_mesh.live:
            pred.attach_serving_mesh(self.serving_mesh)
        srv = InferenceServer(
            pred, max_batch_size=self._max_batch_size,
            seq_buckets=self._seq_buckets, seq_axis=self._seq_axis,
            name=self._name, ready_requires_warmup=True, start=True)
        fp = pred.artifact_fingerprint()
        version = os.path.basename(str(model_prefix)) + \
            (f"@{fp[:8]}" if fp else "")
        return srv, version

    # ---- service surface ----
    def submit_many(self, feeds_list, timeout_ms=None,
                    trace_contexts=None):
        futs = self._server.submit_many(feeds_list,
                                        timeout_ms=timeout_ms,
                                        trace_contexts=trace_contexts)
        # wedge ledger: one in-flight entry per batch, closed when the
        # LAST future resolves — a batch that never resolves is the
        # hang signature the watchdog fires on
        if futs:
            token = self.wedge_monitor.begin()
            pending = {"n": len(futs)}
            plock = threading.Lock()

            def _done(_):
                with plock:
                    pending["n"] -= 1
                    last = pending["n"] == 0
                if last:
                    self.wedge_monitor.end(token)

            for f in futs:
                f.add_done_callback(_done)
        return futs

    def generate(self, prompt, max_new_tokens, temperature, timeout_ms,
                 seed, deadline_ms=None, tenant=None):
        if self._gen is None:
            raise RuntimeError("this replica hosts no generation "
                               "engine (start it with a generation "
                               "model)")
        return self._gen.submit_generate(
            prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, timeout_ms=timeout_ms, seed=seed,
            deadline_ms=deadline_ms, tenant=tenant)

    def warmup(self) -> int:
        """Warm per ``warmup_mode``: "manifest" replays the persisted
        traffic signatures (the warm scale-out path), "lattice" the
        full bucket lattice, "auto" manifest-when-present else
        lattice, "none" flips ready without compiling."""
        return self._warm_server(self._server)

    def _warm_server(self, srv) -> int:
        mode = self._warmup_mode
        n = 0
        if mode == "none":
            srv.mark_ready()
        elif mode == "manifest":
            n = srv.warmup_from_manifest()
            srv.mark_ready()   # empty/absent manifest: nothing to warm
        elif mode == "lattice":
            n = srv.warmup()
        else:   # auto
            manifest = srv.warmup_manifest
            if manifest is not None and len(manifest):
                n = srv.warmup_from_manifest()
            else:
                n = srv.warmup()
        if self._gen is not None and not self._gen.ready:
            n += self._gen.warmup()
        return n

    def ready(self) -> bool:
        with self._lock:
            if self._reloading:
                return False
            srv, gen = self._server, self._gen
        return srv.ready and (gen is None or gen.ready)

    def health(self):
        ok, info = self._server._health()
        return ok, {"server": info}

    def reload(self, model_prefix: str) -> str:
        """Swap to the artifact at ``model_prefix``; returns the new
        version stamp. Failure leaves the current server untouched."""
        with self._lock:
            self._reloading = True
        try:
            new_srv, version = self._build(model_prefix)
            try:
                self._warm_server(new_srv)
            except BaseException:
                new_srv.shutdown(drain=False)
                raise
            with self._lock:
                old, self._server = self._server, new_srv
                self._version = version
            old.shutdown(drain=True)
            return version
        finally:
            with self._lock:
                self._reloading = False

    def info(self) -> dict:
        with self._lock:
            version = self._version
        out = {"backend": "predictor", "version": version,
               "name": self._name,
               "generation": self._gen is not None}
        if self.serving_mesh.live:
            out["serving_mesh"] = self.serving_mesh.statusz()
        return out

    def shutdown(self, drain: bool = True):
        self._server.shutdown(drain=drain)
        if self._gen is not None:
            self._gen.shutdown(drain=drain)


class StubBackend:
    """Accelerator-emulating backend for fleet benches and tests.

    A real replica on an accelerator spends its request latency
    waiting on the device, not burning host CPU — so on a single-core
    CI box the fleet's process-level parallelism is invisible with
    real CPU-bound models (N processes share one core) but entirely
    real in production. The stub reproduces the production shape:
    one "device" per replica (a lock), ``device_ms`` of held-lock
    sleep per dispatched batch of up to ``max_batch`` rows, a bounded
    outstanding budget that sheds with ``QueueFullError`` (HTTP 429
    through the app), deterministic outputs (``x * scale`` with
    ``scale`` derived from the weight version, so a hot swap is
    observable in the payloads), and optional crash triggers for
    failure-path tests. Everything around it — codec, HTTP, router,
    supervisor — is the production code path.
    """

    def __init__(self, *, device_ms: float = 5.0, max_batch: int = 8,
                 queue_capacity: int = 64, warmup_s: float = 0.0,
                 version: str = "v0",
                 crash_value: Optional[float] = None,
                 crash_mode: str = "drop",
                 hang_value: Optional[float] = None,
                 token_ms: Optional[float] = None):
        self.device_ms = float(device_ms)
        self.max_batch = int(max_batch)
        self.queue_capacity = int(queue_capacity)
        self.warmup_s = float(warmup_s)
        self.crash_value = crash_value
        self.crash_mode = crash_mode
        # hang trigger: a feed matching this value wedges the device —
        # the dispatch holds the device lock and never completes (the
        # watchdog's detection target), unlike crash_value's clean exit
        self.hang_value = hang_value
        self.token_ms = (float(token_ms) if token_ms is not None
                         else self.device_ms / 4.0)
        self._lock = threading.Lock()
        self._device = threading.Lock()   # the one emulated device
        self._outstanding = 0
        self._warmed = False
        self._alive = True
        self._wedged = threading.Event()
        self._hang = threading.Event()
        # SDC emulation: "nan" poisons one output element per array,
        # "bitflip" flips one mantissa bit — both silent (the request
        # still succeeds; only the canary probe / numerics tripwires
        # can tell). Set via /chaos, cleared by restore.
        self._corrupt_mode: Optional[str] = None
        self._quarantined = threading.Event()
        self._version = str(version)
        self._scale = self._scale_of(version)
        self.dispatches = 0
        self.wedge_monitor = WedgeMonitor()

    @staticmethod
    def _scale_of(version: str) -> float:
        # deterministic per-version output scale: v0 -> 1.0, v1 -> 2.0
        import zlib
        return 1.0 + (zlib.crc32(str(version).encode()) % 7)

    def _maybe_crash(self, feeds_list):
        if self.crash_value is None:
            return
        for feeds in feeds_list:
            for a in feeds:
                flat = np.asarray(a).ravel()
                if flat.size and float(flat[0]) == self.crash_value:
                    with self._lock:
                        self._alive = False
                        self._warmed = False
                    if self.crash_mode == "exit":
                        os._exit(17)
                    raise _ConnectionDrop("stub crash trigger")

    def mark_wedged(self):
        """Watchdog hook: flip readiness red and wake every thread
        parked on the device lock with the typed error."""
        self._wedged.set()

    def mark_corrupt(self):
        """Canary quarantine hook (``arm_canary`` on_corrupt): flip
        readiness red so the router drains this replica. Unlike a
        wedge, in-flight work completes — corruption is silent, not
        hung — and ``/chaos restore`` lifts the quarantine."""
        self._quarantined.set()

    @staticmethod
    def _corrupt_array(a: np.ndarray, mode: str) -> np.ndarray:
        a = np.array(a, np.float32, copy=True)
        flat = a.ravel()
        if flat.size:
            if mode == "nan":
                flat[0] = np.nan
            elif mode == "bitflip":
                bits = flat[:1].view(np.uint32)
                bits ^= np.uint32(1 << 22)   # one mantissa bit
        return a

    def _apply_corruption(self, arrays):
        with self._lock:
            mode = self._corrupt_mode
        if mode is None:
            return arrays
        return [self._corrupt_array(a, mode) for a in arrays]

    def canary_probe(self) -> dict:
        """Corruption self-check the canary runs instead of a device
        checksum (there is no accelerator here): round-trip a known
        vector through the SAME output path ``submit_many`` uses and
        compare bit-exactly against the host-computed expectation."""
        with self._lock:
            scale = self._scale
        probe = np.arange(8, dtype=np.float32)
        got = self._apply_corruption([probe * scale])[0]
        want = probe * scale
        ok = got.tobytes() == want.tobytes()
        return {"ok": ok,
                "got_sum": float(np.nansum(got)),
                "want_sum": float(want.sum())}

    def _maybe_hang(self, feeds_list):
        if self.hang_value is None:
            return
        for feeds in feeds_list:
            for a in feeds:
                flat = np.asarray(a).ravel()
                if flat.size and float(flat[0]) == self.hang_value:
                    self._hang.set()

    def _device_acquire(self):
        """Wedge-aware device wait: threads queued behind a hung
        dispatch fail with ``ReplicaWedgedError`` the moment the
        watchdog declares the wedge, instead of blocking forever."""
        while not self._device.acquire(timeout=0.05):
            if self._wedged.is_set():
                raise ReplicaWedgedError(
                    "device wedged: dispatch queued behind a hung "
                    "step, replica restarting")
            with self._lock:
                if not self._alive:
                    raise ServerClosedError("stub backend crashed")

    def submit_many(self, feeds_list, timeout_ms=None,
                    trace_contexts=None):
        import concurrent.futures
        n = len(feeds_list)
        with self._lock:
            if not self._alive:
                raise ServerClosedError("stub backend crashed")
            if self._wedged.is_set():
                raise ReplicaWedgedError(
                    "device wedged, replica restarting")
            if self._outstanding + n > self.queue_capacity:
                raise QueueFullError(
                    f"stub at capacity ({self.queue_capacity})")
            self._outstanding += n
            scale = self._scale
        try:
            self._maybe_crash(feeds_list)
            self._maybe_hang(feeds_list)
            batches = -(-n // self.max_batch)
            self._device_acquire()  # one device: dispatches serialize
            token = self.wedge_monitor.begin()
            try:
                if self._hang.is_set():
                    # the wedge: hold the device without completing
                    # until the watchdog fires (or shutdown). The
                    # hung dispatch then DROPS its connection (like
                    # the restarting process it emulates) rather than
                    # answering — a typed 503 would invite the router
                    # to retry the wedge-triggering request onto a
                    # healthy replica and cascade the wedge; only the
                    # WAITERS (which never executed) answer with the
                    # re-routable ReplicaWedgedError
                    while not self._wedged.is_set():
                        with self._lock:
                            if not self._alive:
                                raise ServerClosedError(
                                    "stub backend crashed")
                        time.sleep(0.01)
                    raise _ConnectionDrop("device wedged mid-dispatch")
                time.sleep(self.device_ms * batches / 1e3)
                with self._lock:
                    self.dispatches += batches
            finally:
                self.wedge_monitor.end(token)
                self._device.release()
            futs = []
            for feeds in feeds_list:
                f = concurrent.futures.Future()
                f.set_result(self._apply_corruption(
                    [np.asarray(a, np.float32) * scale
                     for a in feeds]))
                futs.append(f)
            return futs
        finally:
            with self._lock:
                self._outstanding -= n

    def generate(self, prompt, max_new_tokens, temperature, timeout_ms,
                 seed, deadline_ms=None, tenant=None):
        from ..generation.engine import StreamingFuture
        fut = StreamingFuture()
        prompt = np.asarray(prompt).ravel()
        base = int(prompt[-1]) if prompt.size else 0
        hard_deadline = (time.monotonic() + float(deadline_ms) / 1e3
                         if deadline_ms else None)

        def _stream():
            for i in range(int(max_new_tokens)):
                time.sleep(self.token_ms / 1e3)
                if hard_deadline is not None and \
                        time.monotonic() > hard_deadline:
                    fut._fail(DeadlineExceededError(
                        "deadline budget expired mid-stream"),
                        reason="deadline")
                    return
                fut._emit((base + 1 + i) % 50000)
                if fut._cancel_requested:
                    fut._finish("cancelled")
                    return
            fut._finish("length")

        threading.Thread(target=_stream, daemon=True).start()
        return fut

    def chaos(self, spec: dict) -> dict:
        """Runtime fault injection (the /chaos control plane the
        chaos harness drives): ``{"device_ms": X}`` inflates per-batch
        device latency (the slow-replica fault), ``{"capacity": N}``
        resizes the shed threshold (0 = reject storm),
        ``{"hang": true}`` wedges the device,
        ``{"corrupt": "nan"|"bitflip"}`` silently corrupts outputs
        (the SDC-drill fault the canary must catch),
        ``{"restore": true}`` lifts latency/capacity/corruption
        faults. Returns the live settings."""
        with self._lock:
            if spec.get("restore"):
                self.device_ms = float(spec.get(
                    "device_ms", self.device_ms))
                self.queue_capacity = int(spec.get(
                    "capacity", self.queue_capacity))
                self._corrupt_mode = None
            else:
                if "device_ms" in spec:
                    self.device_ms = float(spec["device_ms"])
                if "capacity" in spec:
                    self.queue_capacity = int(spec["capacity"])
                if "corrupt" in spec:
                    mode = spec["corrupt"]
                    if mode not in (None, "nan", "bitflip"):
                        raise ValueError(
                            f"unknown corrupt mode {mode!r}")
                    self._corrupt_mode = mode
        if spec.get("restore"):
            self._quarantined.clear()
        if spec.get("hang"):
            self._hang.set()
        return {"device_ms": self.device_ms,
                "capacity": self.queue_capacity,
                "hang": self._hang.is_set(),
                "wedged": self._wedged.is_set(),
                "corrupt": self._corrupt_mode}

    def warmup(self) -> int:
        if self.warmup_s:
            time.sleep(self.warmup_s)
        with self._lock:
            self._warmed = True
        return 0

    def ready(self) -> bool:
        if self._wedged.is_set() or self._quarantined.is_set():
            return False
        with self._lock:
            return self._warmed and self._alive

    def health(self):
        if self._wedged.is_set():
            return False, "wedged"
        with self._lock:
            if not self._alive:
                return False, "crashed"
            return True, {"outstanding": self._outstanding}

    def reload(self, model_prefix: str) -> str:
        version = os.path.basename(str(model_prefix))
        with self._device:      # a swap waits out the in-flight batch
            with self._lock:
                self._version = version
                self._scale = self._scale_of(version)
        return version

    def info(self) -> dict:
        with self._lock:
            return {"backend": "stub", "version": self._version,
                    "device_ms": self.device_ms,
                    "outstanding": self._outstanding,
                    "dispatches": self.dispatches}

    def shutdown(self, drain: bool = True):
        with self._lock:
            self._alive = False

    @property
    def version(self) -> str:
        with self._lock:
            return self._version


# ---------------------------------------------------------------- app
class _ReplicaHandler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-replica/1.0"

    # ---- plumbing ----
    def _send(self, code: int, body: bytes,
              ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj, sort_keys=True).encode())

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    @property
    def _backend(self):
        return self.server.backend  # type: ignore[attr-defined]

    def log_message(self, *args):
        pass

    # ---- control plane ----
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            if path == "/tracez":
                # this process's flight recorder — the router's merged
                # /tracez fans out to every replica's
                from ...observability.httpd import tracez_text
                self._send(200, tracez_text(query).encode(),
                           "application/json")
            elif path == "/sloz":
                # this process's SLO evaluation — the router's merged
                # /sloz sums window counts across replicas
                from ...observability.slo import sloz_payload
                self._send(200, json.dumps(
                    sloz_payload(), sort_keys=True).encode(),
                    "application/json")
            elif path == "/schedz":
                # this process's admission/autoscaler state — the
                # router's merged /schedz sums tenant events fleet-wide
                from ..scheduling.schedz import schedz_payload
                _worker_scheduler()   # ensure the gate is registered
                self._send(200, json.dumps(
                    schedz_payload(), sort_keys=True).encode(),
                    "application/json")
            elif path == "/goodputz":
                from ...observability.goodput import goodputz_payload
                self._send(200, json.dumps(
                    goodputz_payload(), sort_keys=True).encode(),
                    "application/json")
            elif path == "/execz":
                # this replica's executable cost/roofline registry —
                # the router's /execz aggregates across replicas
                from ...observability.httpd import execz_text
                self._send(200, execz_text(query).encode(),
                           "application/json")
            elif path == "/profilez":
                # list the capture ring, or (?duration_ms=) run one
                # bounded capture on THIS replica and stream it back
                from ...observability.httpd import profilez_response
                code, body = profilez_response(query)
                self._send(code, body.encode(), "application/json")
            elif path == "/numericsz":
                # this replica's numerics/SDC plane — the router's
                # merged /numericsz rolls it up fleet-wide. THIS
                # replica's canary runner overlays the process-global
                # canary section: with several in-process replicas
                # (ThreadReplicaFactory) the shared state would report
                # the LAST sweep of any of them, not this one's.
                from ...observability.httpd import numericsz_text
                doc = json.loads(numericsz_text(query))
                cn = getattr(self.server.app, "canary", None)
                if cn is not None:
                    doc["canary"] = dict(
                        doc.get("canary") or {},
                        corrupt=cn.corrupt, last=cn.last)
                self._send(200, json.dumps(
                    doc, sort_keys=True).encode(), "application/json")
            elif path == "/healthz":
                ok, info = self._backend.health()
                self._send_json(200 if ok else 503,
                                {"ok": ok, "info": info})
            elif path == "/readyz":
                wd = getattr(self.server.app, "watchdog", None)
                wedged = wd is not None and wd.wedged
                cn = getattr(self.server.app, "canary", None)
                corrupt = cn is not None and cn.corrupt
                ready = (self._backend.ready() and not wedged
                         and not corrupt)
                body = {"ready": ready,
                        "version": self._backend.info().get("version")}
                if wedged:
                    body["wedged"] = True
                if corrupt:
                    # the router's poller opens this replica's breaker
                    # on the flag — SDC quarantine, not just not-ready
                    body["corrupt"] = True
                self._send_json(200 if ready else 503, body)
            elif path == "/metrics":
                from ...observability import (default_registry,
                                              prometheus_text)
                from ...observability.exposition import \
                    PROMETHEUS_CONTENT_TYPE
                self._send(200,
                           prometheus_text(default_registry()).encode(),
                           PROMETHEUS_CONTENT_TYPE)
            elif path == "/statusz":
                self._send_json(200, self._backend.info())
            else:
                self._send(404, b"not found\n", "text/plain")
        except _ConnectionDrop:
            self.close_connection = True
        except Exception as e:  # noqa: BLE001 - a probe bug must not
            try:                # kill the handler thread
                self._send(500, f"{e!r}\n".encode(), "text/plain")
            except Exception:  # noqa: BLE001
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        try:
            if path == "/submit_many":
                self._submit_many(query)
            elif path == "/generate":
                self._generate()
            elif path == "/reload":
                req = json.loads(self._body() or b"{}")
                version = self._backend.reload(req["model_prefix"])
                self._send_json(200, {"ok": True, "version": version})
            elif path == "/chaos":
                # stub-only fault-injection control plane (the chaos
                # harness drives slow/reject/hang at runtime)
                chaos = getattr(self._backend, "chaos", None)
                if chaos is None:
                    self._send(501, b"backend has no chaos surface\n",
                               "text/plain")
                else:
                    self._send_json(200, chaos(
                        json.loads(self._body() or b"{}")))
            elif path == "/shutdown":
                self._send_json(200, {"ok": True})
                self.server.app._request_shutdown()  # type: ignore
            else:
                self._send(404, b"not found\n", "text/plain")
        except _ConnectionDrop:
            # crash simulation: vanish mid-request, no response bytes
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
        except QueueFullError as e:
            self._send(429, f"{e}\n".encode(), "text/plain")
        except ReplicaWedgedError as e:
            # wedged = unavailable for anything not already riding the
            # hung dispatch: 503 so the router re-routes safely
            self._send(503, f"{e}\n".encode(), "text/plain")
        except ServerClosedError as e:
            self._send(503, f"{e}\n".encode(), "text/plain")
        except Exception as e:  # noqa: BLE001 - fault barrier for the
            try:                # handler thread
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain")
            except Exception:  # noqa: BLE001
                pass

    # ---- data plane ----
    def _submit_many(self, query: str):
        timeout_ms = None
        for part in query.split("&"):
            if part.startswith("timeout_ms="):
                timeout_ms = float(part.split("=", 1)[1]) or None
        feeds_list, traceparents, deadlines, tenants = \
            codec.decode_batch_trailers_ex(self._body())
        ctxs = [tracing.parse_traceparent(tp) if tp else None
                for tp in (traceparents or [])] or None
        # deadline gate BEFORE dispatch: a request whose budget is
        # already exhausted on arrival is answered now and never
        # reaches the device — expiry-at-the-batcher was the only
        # check before deadline propagation landed
        slots: List[Optional[BaseException]] = [None] * len(feeds_list)
        if deadlines is not None:
            expired = [i for i, ms in enumerate(deadlines)
                       if ms is not None and ms <= 0.0]
            for i in expired:
                slots[i] = DeadlineExceededError(
                    "deadline budget exhausted before worker "
                    "dispatch")
            if expired:
                _worker_metrics().count_deadline_reject(len(expired))
        # per-tenant quota gate, AFTER the deadline gate (an already-
        # dead request must not debit its tenant's bucket) and before
        # dispatch: a shed rides the results framing as the typed
        # QuotaExceededError (codec status _ERR_QUOTA), so one noisy
        # tenant never fails its batch peers. Untagged requests map
        # to the 'default' tenant deterministically.
        sched = _worker_scheduler()
        tlist = tenants if tenants is not None \
            else [None] * len(feeds_list)
        for i, t in enumerate(tlist):
            if slots[i] is None:
                try:
                    sched.admit(t, cost=1.0)
                except QuotaExceededError as e:
                    slots[i] = e
        if any(s is not None for s in slots):
            keep = [i for i in range(len(feeds_list))
                    if slots[i] is None]
            feeds_list = [feeds_list[i] for i in keep]
            if ctxs is not None:
                ctxs = [ctxs[i] for i in keep]
        if deadlines is not None:
            live = [ms for i, ms in enumerate(deadlines)
                    if slots[i] is None and ms is not None
                    and ms > 0.0]
            if live:
                # the replica-side scheduling timeout honors the
                # tightest surviving budget
                tight = min(live)
                timeout_ms = tight if timeout_ms is None \
                    else min(timeout_ms, tight)
        if feeds_list:
            lead = next((c for c in (ctxs or []) if c is not None),
                        None)
            if lead is None:
                futs = self._backend.submit_many(
                    feeds_list, timeout_ms=timeout_ms)
                results = self._collect(futs)
            else:
                # one worker-side span per handled batch; requests in
                # the same trace re-parent under it so the stitched
                # view shows router -> worker -> engine stages
                with tracing.start_span(
                        "worker::submit_many", stage="worker",
                        ctx=lead,
                        attrs={"n_req": len(feeds_list),
                               "replica": self._backend.info().get(
                                   "name") or self._backend.info().get(
                                   "version", "")}) as sp:
                    ctxs = [sp.ctx if (c is not None and
                                       c.trace_id == sp.ctx.trace_id)
                            else c for c in ctxs]
                    futs = self._backend.submit_many(
                        feeds_list, timeout_ms=timeout_ms,
                        trace_contexts=ctxs)
                    results = self._collect(futs)
                    if any(isinstance(res, BaseException)
                           for res in results):
                        sp.set_attr("partial_failure", True)
        else:
            results = []
        it = iter(results)
        merged = [slot if slot is not None else next(it)
                  for slot in slots]
        self._send(200, codec.encode_results(merged),
                   "application/x-paddle-fleet")

    def _collect(self, futs):
        results = []
        for f in futs:
            try:
                results.append(f.result(timeout=self.server.app
                                        .request_timeout_s))
            except BaseException as e:  # noqa: BLE001 - per-request
                results.append(e)       # failures ride the framing
        return results

    def _generate(self):
        req = json.loads(self._body() or b"{}")
        # ambient context for the submit: GenerationServer captures it
        # into the request, so decode spans land in the caller's trace
        ctx = tracing.parse_traceparent(req.get("traceparent"))
        # tenant: JSON field wins, else the x-paddle-tenant header
        # (the router stamps the field; raw clients send the header)
        tenant = req.get("tenant") or \
            self.headers.get("x-paddle-tenant")
        kwargs = {"deadline_ms": req.get("deadline_ms")}
        if tenant is not None:
            # tenant-blind backends (pre-PDTN generate signature)
            # keep working: only pass the kwarg when they take it
            import inspect
            try:
                params = inspect.signature(
                    self._backend.generate).parameters
                if "tenant" in params or any(
                        p.kind == p.VAR_KEYWORD
                        for p in params.values()):
                    kwargs["tenant"] = tenant
            except (TypeError, ValueError):
                kwargs["tenant"] = tenant
        with tracing.use_context(ctx):
            fut = self._backend.generate(
                np.asarray(req["prompt"], np.int64),
                int(req.get("max_new_tokens", 32)),
                float(req.get("temperature", 0.0)),
                req.get("timeout_ms"), req.get("seed"), **kwargs)
        # close-delimited stream: one JSON line per token event, then
        # the terminal line with the finish reason
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        try:
            for tok in fut:
                self.wfile.write(
                    json.dumps({"t": int(tok)}).encode() + b"\n")
                self.wfile.flush()
            self.wfile.write(json.dumps(
                {"done": True,
                 "finish_reason": fut.finish_reason}).encode() + b"\n")
        except BrokenPipeError:
            fut.cancel()        # client went away: stop generating
        except BaseException as e:  # noqa: BLE001 - stream the error
            reason = "deadline" \
                if isinstance(e, DeadlineExceededError) else "error"
            try:
                self.wfile.write(json.dumps(
                    {"done": True, "finish_reason": reason,
                     "error": f"{type(e).__name__}: {e}"}).encode()
                    + b"\n")
            except OSError:
                pass


class ReplicaApp:
    """One ThreadingHTTPServer bound to a backend, on a daemon
    thread. ``port=0`` binds ephemeral; read ``.port`` / ``.url``
    back."""

    def __init__(self, backend, host: str = "127.0.0.1",
                 port: int = 0,
                 request_timeout_s: Optional[float] = None):
        self.backend = backend
        self.host = host
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else _flag("FLAGS_fleet_request_timeout_s", 120.0))
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._shutdown_requested = threading.Event()
        self.watchdog: Optional[WedgeWatchdog] = None
        self.canary = None      # CanaryRunner via arm_canary

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ReplicaApp":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _ReplicaHandler)
        httpd.daemon_threads = True
        httpd.backend = self.backend        # type: ignore[attr-defined]
        httpd.app = self                    # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="fleet-replica-http",
            daemon=True)
        self._thread.start()
        return self

    def _request_shutdown(self):
        self._shutdown_requested.set()

    def wait_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown_requested.wait(timeout)

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)


# ---------------------------------------------------------------- local
class ThreadReplicaFactory:
    """Spawns replicas as threads in THIS process — the supervisor's
    test double and the single-process deployment mode. Each "process"
    is a ReplicaApp over a backend built by ``backend_factory``;
    ``kill()`` drops it abruptly (closed sockets, exit code 1), like a
    SIGKILLed worker."""

    def __init__(self, backend_factory):
        self.backend_factory = backend_factory
        self.spawned: List["_ThreadReplica"] = []

    def __call__(self, replica_id: int) -> "_ThreadReplica":
        rep = _ThreadReplica(self.backend_factory(replica_id))
        self.spawned.append(rep)
        return rep


class _ThreadReplica:
    """ReplicaProcess protocol over an in-thread ReplicaApp."""

    def __init__(self, backend):
        self.backend = backend
        self.app = ReplicaApp(backend).start()
        self._rc: Optional[int] = None
        self.pid = -os.getpid()     # marks "not a real process"
        backend.warmup()

    def url(self) -> Optional[str]:
        return self.app.url if self._rc is None else None

    def poll(self) -> Optional[int]:
        if self._rc is None and self.app.wait_shutdown(0):
            self._rc = 0
        return self._rc

    def terminate(self):
        if self._rc is None:
            self.backend.shutdown(drain=True)
            self.app.stop()
            self._rc = 0

    def kill(self):
        if self._rc is None:
            self.backend.shutdown(drain=False)
            self.app.stop()
            self._rc = 1

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        return self.poll()


# ---------------------------------------------------------------- main
def _parse_args(argv):
    import argparse
    ap = argparse.ArgumentParser(
        description="paddle-tpu fleet replica worker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--announce", default=None,
                    help="announce-file path the supervisor polls")
    ap.add_argument("--model-prefix", default=None)
    ap.add_argument("--warmup", default="auto",
                    choices=("auto", "manifest", "lattice", "none"))
    ap.add_argument("--max-batch-size", type=int, default=0)
    ap.add_argument("--seq-buckets", default="",
                    help="comma list, e.g. 8,16,32 ('' = no seq "
                         "bucketing)")
    ap.add_argument("--name", default=None)
    ap.add_argument("--generation-preset", default="",
                    help="'tiny' hosts a seeded gpt_tiny "
                         "GenerationServer next to the predictor")
    ap.add_argument("--stub", action="store_true",
                    help="accelerator-emulating stub backend (no "
                         "model; fleet benches + failure drills)")
    ap.add_argument("--stub-device-ms", type=float, default=5.0)
    ap.add_argument("--stub-max-batch", type=int, default=8)
    ap.add_argument("--stub-capacity", type=int, default=64)
    ap.add_argument("--stub-warmup-s", type=float, default=0.0)
    ap.add_argument("--stub-version", default="v0")
    ap.add_argument("--stub-crash-value", type=float, default=None)
    ap.add_argument("--stub-crash-mode", default="exit",
                    choices=("exit", "drop"))
    ap.add_argument("--stub-hang-value", type=float, default=None,
                    help="a feed matching this value wedges the "
                         "stub's device (the dispatch never "
                         "completes; the wedge watchdog's target)")
    ap.add_argument("--wedge-timeout-ms", type=float, default=None,
                    help="device-wedge watchdog timeout (default: "
                         "FLAGS_fleet_wedge_timeout_ms; <= 0 off)")
    ap.add_argument("--canary-period-s", type=float, default=None,
                    help="numerics SDC canary sweep period (default: "
                         "FLAGS_numerics_canary_period_s; <= 0 off)")
    return ap.parse_args(argv)


def _build_backend(args):
    if args.stub:
        return StubBackend(
            device_ms=args.stub_device_ms,
            max_batch=args.stub_max_batch,
            queue_capacity=args.stub_capacity,
            warmup_s=args.stub_warmup_s,
            version=args.stub_version,
            crash_value=args.stub_crash_value,
            crash_mode=args.stub_crash_mode,
            hang_value=args.stub_hang_value)
    if not args.model_prefix:
        raise SystemExit("worker: need --model-prefix or --stub")
    gen_model = None
    if args.generation_preset:
        import paddle_tpu as paddle
        from ...models import GPTForCausalLM, gpt_tiny
        paddle.seed(0)
        gen_model = GPTForCausalLM(
            gpt_tiny(use_flash_attention=False))
    buckets = [int(b) for b in args.seq_buckets.split(",") if b]
    return PredictorBackend(
        args.model_prefix,
        max_batch_size=args.max_batch_size or None,
        seq_buckets=buckets or None,
        warmup_mode=args.warmup,
        name=args.name or f"replica-{os.getpid()}",
        generation_model=gen_model)


def main(argv=None) -> int:
    import signal

    args = _parse_args(argv)
    tracing.set_process_name(args.name or f"replica-{os.getpid()}")
    backend = _build_backend(args)
    app = ReplicaApp(backend, host=args.host,
                     port=args.port).start()
    # the watchdog turns a wedged device into a bounded failure: flip
    # readiness, fail device waiters, exit — the supervisor respawns
    arm_wedge_watchdog(backend, app,
                       timeout_ms=args.wedge_timeout_ms)
    # the canary turns silent data corruption into the same bounded,
    # observable failure: readiness red, router breaker open
    arm_canary(backend, app, period_s=args.canary_period_s)
    if args.announce:
        write_announce_file(args.announce, app.port)

    def _sigterm(signum, frame):
        app._request_shutdown()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
        signal.signal(signal.SIGINT, _sigterm)
    except ValueError:
        pass    # not the main thread (embedded use)
    # liveness is up (the app answers /healthz) but readiness stays
    # false until this warmup pass — the whole point of the split
    backend.warmup()
    app.wait_shutdown()
    backend.shutdown(drain=True)
    app.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
