"""Serving-resilience primitives: deadlines, breakers, backoff, wedge.

The fleet handles *clean* failures structurally (a crashed replica is
respawned and routed around); this module supplies the pieces for the
dirty ones — slow, wedged, or partially-failed replicas — that PERF.md
history shows are what this stack actually hits (the r04 wedged
backend, the r05 wedged tunnel):

- ``Deadline`` — one request's absolute time budget, carried across
  hops. Each hop deducts elapsed wall time (``remaining_ms``), so a
  request admitted at the router with 50 ms left arrives at the worker
  with what is actually left, and the worker can reject already-expired
  work BEFORE dispatching it to the device.
- ``CircuitBreaker`` — per-replica rolling outcome window with the
  classic closed -> open -> half-open -> closed state machine. Opens on
  an error ratio over a minimum sample count; a latency threshold makes
  slow-but-alive count as failure (readiness alone cannot drain a
  replica that answers /readyz green while serving 100x latency).
  Half-open admits ONE probe at a time; a probe success closes, a
  probe failure re-opens with the cooldown reset.
- ``retry_backoff_ms`` — exponential backoff with full jitter for the
  router's retry loop, replacing the fixed immediate-retry
  ``FLAGS_fleet_retries`` spin (which turns a fleet-wide brownout into
  a synchronized retry storm).
- ``ReplicaWedgedError`` — the typed error a wedge turns into: raised
  to requests waiting on a wedged device and round-tripped through the
  fleet codec, so callers can tell "the device hung" from "the queue
  was full".
- ``WedgeMonitor`` / ``WedgeWatchdog`` — dispatch-level hang
  detection. Backends bracket device work with ``begin()``/``end()``;
  the watchdog thread flags the replica wedged when the oldest
  in-flight dispatch exceeds ``FLAGS_fleet_wedge_timeout_ms`` (a
  dispatch that never completes is exactly the "stepprof envelopes
  stopped flowing" signal at the layer the worker controls), flips
  readiness, fails waiting requests, and triggers the restart callback
  so the supervisor's respawn path turns a silent hang into a bounded,
  observable failure.

Everything here is stdlib-only and lock-guarded; the router, worker
and chaos harness (tools/chaos_fleet.py) share these exact objects, so
the behavior the harness proves is the behavior production runs.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

__all__ = ["Deadline", "CircuitBreaker", "ReplicaWedgedError",
           "WedgeMonitor", "WedgeWatchdog", "retry_backoff_ms",
           "latency_quantile"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class ReplicaWedgedError(RuntimeError):
    """The replica's device wedged (a dispatch exceeded the wedge
    timeout): the request did not complete and the replica is
    restarting. Distinct from QueueFullError (backpressure) and
    ServerClosedError (clean shutdown) so callers and the router can
    react differently."""


# ---------------------------------------------------------------- deadline
class Deadline:
    """An absolute per-request time budget on the monotonic clock.

    Wire form is RELATIVE (``remaining_ms``) because wall clocks of
    router and worker processes are not comparable; each hop
    reconstructs its own absolute deadline from what is left when the
    payload arrives. ``None`` budget = no deadline (infinite)."""

    __slots__ = ("_at",)

    def __init__(self, budget_ms: Optional[float]):
        self._at = (time.monotonic() + float(budget_ms) / 1e3
                    if budget_ms else None)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    @property
    def bounded(self) -> bool:
        return self._at is not None

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds left (may be negative once expired); None for
        an unbounded deadline."""
        if self._at is None:
            return None
        return (self._at - time.monotonic()) * 1e3

    def expired(self) -> bool:
        return self._at is not None and time.monotonic() > self._at

    def clamp_ms(self, ms: float) -> float:
        """``ms`` bounded by what is left of the budget (>= 0)."""
        rem = self.remaining_ms()
        if rem is None:
            return ms
        return max(0.0, min(ms, rem))


# ---------------------------------------------------------------- backoff
def retry_backoff_ms(attempt: int, base_ms: float, max_ms: float,
                     rng: Optional[random.Random] = None) -> float:
    """Exponential backoff with FULL jitter: uniform over
    [0, min(max, base * 2^attempt)]. Full jitter decorrelates the
    fleet's retries — under a brownout every un-jittered client
    re-dispatches on the same schedule and the retry wave re-creates
    the overload it is retrying around."""
    cap = min(float(max_ms), float(base_ms) * (2.0 ** max(0, attempt)))
    r = rng.random() if rng is not None else random.random()
    return cap * r


def latency_quantile(samples, q: float) -> Optional[float]:
    """Nearest-rank quantile of an iterable of latencies (ms); None
    when empty."""
    xs = sorted(samples)
    if not xs:
        return None
    idx = min(len(xs) - 1, max(0, int(q * len(xs))))
    return float(xs[idx])


# ---------------------------------------------------------------- breaker
class CircuitBreaker:
    """Per-replica health memory: a rolling window of request outcomes
    driving closed/open/half-open admission.

    - record(ok, latency_ms): every finished dispatch reports here. A
      success slower than ``latency_threshold_ms`` (when > 0) counts
      as a FAILURE — the slow-but-alive signal readiness misses.
    - allow(): whether a new dispatch may go to this replica. Closed:
      yes. Open: no until ``open_ms`` elapsed, then the breaker moves
      to half-open and admits exactly ONE in-flight probe. Half-open:
      only the probe slot.
    - The probe's outcome closes (success) or re-opens (failure) the
      breaker; ``on_transition(old, new)`` fires outside the lock for
      metrics.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, *, window: Optional[int] = None,
                 failure_ratio: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 open_ms: Optional[float] = None,
                 latency_threshold_ms: Optional[float] = None,
                 on_transition: Optional[Callable] = None):
        self.window = int(window if window is not None
                          else _flag("FLAGS_fleet_breaker_window", 16))
        self.failure_ratio = float(
            failure_ratio if failure_ratio is not None
            else _flag("FLAGS_fleet_breaker_failure_ratio", 0.5))
        self.min_samples = int(
            min_samples if min_samples is not None
            else _flag("FLAGS_fleet_breaker_min_samples", 4))
        self.open_ms = float(
            open_ms if open_ms is not None
            else _flag("FLAGS_fleet_breaker_open_ms", 1000.0))
        self.latency_threshold_ms = float(
            latency_threshold_ms if latency_threshold_ms is not None
            else _flag("FLAGS_fleet_breaker_latency_ms", 0.0))
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._outcomes: "deque[bool]" = deque(maxlen=max(1, self.window))
        self._latencies: "deque[float]" = deque(
            maxlen=max(1, self.window))
        self._opened_at = 0.0
        self._probe_inflight = False
        self._opens = 0

    # ---- inspection ----
    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    @property
    def opens(self) -> int:
        with self._lock:
            return self._opens

    def latency_window(self) -> List[float]:
        with self._lock:
            return list(self._latencies)

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self._outcomes)
            fails = sum(1 for ok in self._outcomes if not ok)
            return {"state": self._effective_state(),
                    "samples": n, "failures": fails,
                    "failure_ratio": (fails / n) if n else 0.0,
                    "opens": self._opens,
                    "open_remaining_ms": max(
                        0.0, (self._opened_at + self.open_ms / 1e3
                              - time.monotonic()) * 1e3)
                    if self._state == self.OPEN else 0.0}

    # ---- state machine ----
    def _effective_state(self) -> str:
        """Lock held. OPEN lazily decays to HALF_OPEN after the
        cooldown (no timer thread)."""
        if self._state == self.OPEN and \
                time.monotonic() - self._opened_at >= self.open_ms / 1e3:
            self._state = self.HALF_OPEN
            self._probe_inflight = False
        return self._state

    def allow(self) -> bool:
        """May a dispatch go to this replica now? In half-open this
        CONSUMES the single probe slot — callers that end up not
        dispatching must record an outcome or call ``release_probe``."""
        with self._lock:
            state = self._effective_state()
            if state == self.CLOSED:
                return True
            if state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def release_probe(self):
        """Return an unused half-open probe slot (the caller took
        ``allow()`` but never dispatched)."""
        with self._lock:
            self._probe_inflight = False

    def record(self, ok: bool, latency_ms: Optional[float] = None):
        effective_ok = bool(ok)
        if effective_ok and latency_ms is not None and \
                self.latency_threshold_ms > 0 and \
                latency_ms > self.latency_threshold_ms:
            effective_ok = False    # slow-but-alive counts as failure
        transition: Optional[Tuple[str, str]] = None
        with self._lock:
            state = self._effective_state()
            if latency_ms is not None and ok:
                self._latencies.append(float(latency_ms))
            self._outcomes.append(effective_ok)
            if state == self.HALF_OPEN:
                self._probe_inflight = False
                if effective_ok:
                    self._state = self.CLOSED
                    self._outcomes.clear()
                    transition = (self.HALF_OPEN, self.CLOSED)
                else:
                    self._state = self.OPEN
                    self._opened_at = time.monotonic()
                    self._opens += 1
                    transition = (self.HALF_OPEN, self.OPEN)
            elif state == self.CLOSED:
                n = len(self._outcomes)
                fails = sum(1 for o in self._outcomes if not o)
                if n >= self.min_samples and \
                        fails / n >= self.failure_ratio:
                    self._state = self.OPEN
                    self._opened_at = time.monotonic()
                    self._opens += 1
                    transition = (self.CLOSED, self.OPEN)
        if transition is not None and self.on_transition is not None:
            try:
                self.on_transition(*transition)
            except Exception:  # noqa: BLE001 - metrics must not break
                pass           # the data plane

    def force_open(self):
        """Open immediately (the watchdog's shortcut when a wedge is
        detected by other means)."""
        transition = None
        with self._lock:
            if self._state != self.OPEN:
                transition = (self._effective_state(), self.OPEN)
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._opens += 1
        if transition is not None and self.on_transition is not None:
            try:
                self.on_transition(*transition)
            except Exception:  # noqa: BLE001 - as above
                pass


# ---------------------------------------------------------------- wedge
class WedgeMonitor:
    """In-flight dispatch ledger a backend brackets device work with:

        token = monitor.begin()
        try:    ... device dispatch ...
        finally: monitor.end(token)

    ``oldest_age_ms()`` is what the watchdog polls: the age of the
    longest-running still-open dispatch (0 when idle). A dispatch that
    never calls ``end`` makes the age grow without bound — exactly the
    wedge signature."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict = {}
        self._seq = 0
        self._completed = 0

    def begin(self) -> int:
        with self._lock:
            self._seq += 1
            token = self._seq
            self._inflight[token] = time.monotonic()
            return token

    def end(self, token: int):
        with self._lock:
            if self._inflight.pop(token, None) is not None:
                self._completed += 1

    def oldest_age_ms(self) -> float:
        with self._lock:
            if not self._inflight:
                return 0.0
            return (time.monotonic() - min(self._inflight.values())) \
                * 1e3

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    @property
    def completed(self) -> int:
        with self._lock:
            return self._completed


class WedgeWatchdog:
    """Heartbeat thread over a ``WedgeMonitor``: when the oldest
    in-flight dispatch exceeds ``timeout_ms``, the watchdog (once)
    marks itself wedged, calls ``on_wedge()`` — the worker's hook to
    flip /readyz, fail waiting requests with ``ReplicaWedgedError``
    and ask for a restart — and keeps the wedged flag up so readiness
    stays red until the process is replaced. ``timeout_ms <= 0``
    disables the thread entirely (construction is still cheap)."""

    def __init__(self, monitor: WedgeMonitor, *,
                 timeout_ms: Optional[float] = None,
                 poll_interval_s: float = 0.05,
                 on_wedge: Optional[Callable] = None,
                 name: str = "replica"):
        self.monitor = monitor
        self.timeout_ms = float(
            timeout_ms if timeout_ms is not None
            else _flag("FLAGS_fleet_wedge_timeout_ms", 0.0))
        self.poll_interval_s = float(poll_interval_s)
        self.on_wedge = on_wedge
        self.name = name
        self._wedged = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wedge_count = 0

    @property
    def enabled(self) -> bool:
        return self.timeout_ms > 0

    @property
    def wedged(self) -> bool:
        return self._wedged.is_set()

    @property
    def wedge_count(self) -> int:
        return self._wedge_count

    def start(self) -> "WedgeWatchdog":
        if self.enabled and (self._thread is None
                             or not self._thread.is_alive()):
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"fleet-wedge-watchdog-{self.name}")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() and \
                t is not threading.current_thread():
            t.join(timeout=5)

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            if self._wedged.is_set():
                continue        # one firing per process lifetime
            if self.monitor.oldest_age_ms() > self.timeout_ms:
                self._fire()

    def _fire(self):
        self._wedged.set()
        self._wedge_count += 1
        if self.on_wedge is not None:
            try:
                self.on_wedge()
            except Exception:  # noqa: BLE001 - the watchdog must not
                pass           # die on a broken recovery hook
