"""paddle_tpu.serving.fleet — multi-replica serving.

One ``InferenceServer`` process tops out at one GIL and one device
queue; production traffic needs N of them behind one front end. This
package is that fleet:

- ``ReplicaSupervisor`` (supervisor.py) spawns and keeps alive N
  replica worker processes (``worker.py`` run as
  ``python -m paddle_tpu.serving.fleet.worker``), each hosting an
  ``InferenceServer`` (and optionally a ``GenerationServer``) warmed
  from the shared ``FLAGS_compile_cache_dir`` + warmup manifest — so
  scale-out and crash recovery are warm starts, and a crashed
  replica is respawned automatically.
- ``FleetRouter`` (router.py) load-balances ``submit`` /
  ``submit_many`` / ``submit_generate`` across replicas
  (least-outstanding), routes only to READY replicas (readiness =
  warmup complete, split from liveness — see ``/readyz``), sheds
  load by retrying a replica's ``QueueFullError`` elsewhere before
  failing the batch, streams decode tokens back per request, and
  performs the rolling hot weight swap (``swap_weights``): drain one
  replica, ``/reload`` the version-stamped artifact, verify ready,
  next — zero downtime, zero failed in-flight requests.
- ``RouterApp`` / ``ReplicaApp`` are the stdlib-HTTP shells (same
  plumbing family as ``observability.httpd``); ``codec.py`` is the
  explicit binary wire format (no pickle on sockets).
- ``FleetMetrics`` (metrics.py) exports the ``paddle_fleet_*``
  families on the PR 3 registry; the router's
  ``/metrics?merged=1`` view re-labels every replica's own scrape
  with ``replica="<id>"``.
- Distributed request tracing
  (``paddle_tpu.observability.tracing``): the router mints a
  per-request trace context at ingress, stamps it onto the wire
  (codec trace trailer / worker JSON), and its ``/tracez`` stitches
  router + replica spans into one cross-process trace; ``/statusz``
  aggregates per-replica readiness/outstanding/restarts/version.

- Resilience layer (resilience.py, PR 15): end-to-end DEADLINE
  propagation (router deducts per hop, codec ``PDDL`` trailer /
  ``deadline_ms`` JSON field, worker rejects expired work before
  dispatch, the generation engine evicts expired in-flight streams
  with their pages freed); per-replica CIRCUIT BREAKERS with
  half-open probing (slow-but-alive replicas drain even while
  ``/readyz`` stays green); exponential-backoff-with-jitter retries;
  HEDGED ``submit``/``submit_many`` (first response wins, duplicate
  execution accounted in ``paddle_fleet_hedges_total``); and the
  DEVICE-WEDGE WATCHDOG (``arm_wedge_watchdog``) that turns a hung
  dispatch into a typed ``ReplicaWedgedError`` + supervisor respawn.
  Proven by ``tools/chaos_fleet.py`` (CHAOS_r01.json, perfci-gated).

Knobs: ``FLAGS_fleet_*`` + ``FLAGS_serving_ready_requires_warmup``
in framework/flags.py. Bench: ``tools/bench_fleet.py``.
"""
from __future__ import annotations

from . import codec  # noqa: F401
from . import resilience  # noqa: F401
from .metrics import FleetMetrics, merge_prometheus_texts
from .resilience import (CircuitBreaker, Deadline, ReplicaWedgedError,
                         WedgeMonitor, WedgeWatchdog)
from .router import (FleetRouter, NoReadyReplicaError, ReplicaError,
                     RouterApp)
from .supervisor import (ProcessReplicaFactory, ReplicaSupervisor,
                         SubprocessReplica)
from .worker import (PredictorBackend, ReplicaApp, StubBackend,
                     ThreadReplicaFactory, arm_canary,
                     arm_wedge_watchdog)

__all__ = [
    "FleetRouter", "RouterApp", "ReplicaSupervisor",
    "ProcessReplicaFactory", "SubprocessReplica", "ReplicaApp",
    "PredictorBackend", "StubBackend", "ThreadReplicaFactory",
    "FleetMetrics", "merge_prometheus_texts", "NoReadyReplicaError",
    "ReplicaError", "codec", "resilience", "CircuitBreaker",
    "Deadline", "ReplicaWedgedError", "WedgeMonitor", "WedgeWatchdog",
    "arm_wedge_watchdog", "arm_canary",
]
