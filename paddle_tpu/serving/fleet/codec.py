"""Wire codec for the fleet data plane: raw-buffer array framing.

The router forwards request batches to replica workers over plain
HTTP; the payload is numpy arrays. JSON-of-nested-lists would burn the
router's single thread-pool CPU on float formatting, and pickle would
widen the trusted surface from "the compile-cache directory" to "every
socket peer" — so the wire format is a minimal explicit framing of
``(dtype, shape, raw C-contiguous bytes)``, decodable with
``np.frombuffer`` and nothing else. Only shapes/dtypes/bytes cross the
wire; nothing on the decode path executes content.

Layout (all integers little-endian):

- array:    u8 dtype-str length, dtype.str ascii, u8 ndim,
            u32 x ndim dims, u64 nbytes, raw buffer
- batch:    magic ``PDFB``, u32 n_requests, per request
            (u32 n_feeds, n_feeds arrays)
- results:  magic ``PDFR``, u32 n_requests, per request u8 status —
            0 = ok (u32 n_outputs, arrays) or an error code
            (u32 utf-8 length, message) mapping back to the serving
            exception types, so ``QueueFullError`` raised in a replica
            process is ``QueueFullError`` again out of the router.
- trace trailer (optional): magic ``PDTC`` appended AFTER a batch's
            last request — u32 n_requests, per request u16 length +
            ascii ``traceparent`` (0 = untraced). Append-only, so the
            router can stamp trace contexts onto an opaque client
            body without decoding the arrays, and a decoder that
            ignores it (``decode_batch``) keeps working unchanged.
- deadline trailer (optional): magic ``PDDL`` — u32 n_requests, per
            request f64 REMAINING budget milliseconds (NaN =
            unbounded). Relative-not-absolute because router and
            worker wall clocks are not comparable; each hop deducts
            its own elapsed time before re-stamping. Trailers may
            appear in any order after the batch body; every section
            must parse to exactly EOF.
- tenant trailer (optional): magic ``PDTN`` — u32 n_requests, per
            request u16 length + ascii tenant id (0 = untagged; the
            consumer maps untagged to the ``default`` tenant). Same
            append-only / upstream-stamp-wins discipline as PDTC, so
            a client that tagged its own tenancy is never relabeled
            by the router.
"""
from __future__ import annotations

import math
import re
import struct
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..request import (DeadlineExceededError, QueueFullError,
                       QuotaExceededError, ServerClosedError)
from .resilience import ReplicaWedgedError

__all__ = [
    "encode_batch", "decode_batch", "decode_batch_ex",
    "decode_batch_trailers", "decode_batch_trailers_ex",
    "encode_results", "decode_results",
    "peek_batch_size", "attach_trace_trailer",
    "attach_deadline_trailer", "attach_tenant_trailer", "CodecError",
    "BATCH_MAGIC", "RESULTS_MAGIC", "TRACE_MAGIC", "DEADLINE_MAGIC",
    "TENANT_MAGIC",
]

BATCH_MAGIC = b"PDFB"
RESULTS_MAGIC = b"PDFR"
TRACE_MAGIC = b"PDTC"
DEADLINE_MAGIC = b"PDDL"
TENANT_MAGIC = b"PDTN"

# status codes for per-request results (0 = ok)
_OK = 0
_ERR_GENERIC = 1
_ERR_QUEUE_FULL = 2
_ERR_DEADLINE = 3
_ERR_CLOSED = 4
_ERR_WEDGED = 5
_ERR_QUOTA = 6

# QuotaExceededError subclasses QueueFullError, so _CODE_OF must map
# the SUBCLASS first-match by exact type (dict lookup is exact) — a
# quota shed crosses the wire as _ERR_QUOTA, not _ERR_QUEUE_FULL.
_CODE_OF = {QueueFullError: _ERR_QUEUE_FULL,
            QuotaExceededError: _ERR_QUOTA,
            DeadlineExceededError: _ERR_DEADLINE,
            ServerClosedError: _ERR_CLOSED,
            ReplicaWedgedError: _ERR_WEDGED}
_EXC_OF: Dict[int, type] = {_ERR_QUEUE_FULL: QueueFullError,
                            _ERR_QUOTA: QuotaExceededError,
                            _ERR_DEADLINE: DeadlineExceededError,
                            _ERR_CLOSED: ServerClosedError,
                            _ERR_WEDGED: ReplicaWedgedError,
                            _ERR_GENERIC: RuntimeError}


class CodecError(ValueError):
    """Malformed fleet wire payload."""


def _put_array(parts: List[bytes], a: np.ndarray):
    a = np.ascontiguousarray(a)
    ds = a.dtype.str.encode("ascii")
    parts.append(struct.pack("<B", len(ds)))
    parts.append(ds)
    parts.append(struct.pack("<B", a.ndim))
    parts.append(struct.pack(f"<{a.ndim}I", *a.shape)
                 if a.ndim else b"")
    parts.append(struct.pack("<Q", a.nbytes))
    parts.append(a.tobytes())


class _Reader:
    __slots__ = ("data", "ofs")

    def __init__(self, data: bytes):
        self.data = data
        self.ofs = 0

    def take(self, n: int) -> bytes:
        if self.ofs + n > len(self.data):
            raise CodecError("truncated fleet payload")
        out = self.data[self.ofs:self.ofs + n]
        self.ofs += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def array(self) -> np.ndarray:
        dtype = np.dtype(self.take(self.u8()).decode("ascii"))
        ndim = self.u8()
        shape = struct.unpack(f"<{ndim}I", self.take(4 * ndim)) \
            if ndim else ()
        nbytes = self.u64()
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize \
            if ndim else dtype.itemsize
        if nbytes != want:
            raise CodecError(
                f"array payload {nbytes}B != shape/dtype size {want}B")
        buf = self.take(nbytes)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)


def encode_batch(feeds_list: Sequence[Sequence[np.ndarray]]) -> bytes:
    """Encode a ``submit_many`` batch: a list of per-request feed
    lists (each ordered like the model's feed names)."""
    parts: List[bytes] = [BATCH_MAGIC,
                          struct.pack("<I", len(feeds_list))]
    for feeds in feeds_list:
        parts.append(struct.pack("<I", len(feeds)))
        for a in feeds:
            _put_array(parts, np.asarray(a))
    return b"".join(parts)


def peek_batch_size(data: bytes) -> int:
    """Request count of an encoded batch without decoding the arrays —
    the router's pass-through path needs only this for accounting."""
    if len(data) < 8 or data[:4] != BATCH_MAGIC:
        raise CodecError("not a fleet batch payload")
    return struct.unpack("<I", data[4:8])[0]


def decode_batch(data: bytes) -> List[List[np.ndarray]]:
    r = _Reader(data)
    if r.take(4) != BATCH_MAGIC:
        raise CodecError("not a fleet batch payload")
    return [[r.array() for _ in range(r.u32())]
            for _ in range(r.u32())]


def _parse_trace_section(r: "_Reader", n_req: int):
    n = r.u32()
    if n != n_req:
        raise CodecError(
            f"trace trailer for {n} requests on a batch of {n_req}")
    out = []
    for _ in range(n):
        ln = struct.unpack("<H", r.take(2))[0]
        out.append(r.take(ln).decode("ascii", "replace")
                   if ln else None)
    return out


def _parse_deadline_section(r: "_Reader", n_req: int):
    n = r.u32()
    if n != n_req:
        raise CodecError(
            f"deadline trailer for {n} requests on a batch of {n_req}")
    out = []
    for _ in range(n):
        ms = struct.unpack("<d", r.take(8))[0]
        out.append(None if math.isnan(ms) else float(ms))
    return out


def _parse_tenant_section(r: "_Reader", n_req: int):
    n = r.u32()
    if n != n_req:
        raise CodecError(
            f"tenant trailer for {n} requests on a batch of {n_req}")
    out = []
    for _ in range(n):
        ln = struct.unpack("<H", r.take(2))[0]
        out.append(r.take(ln).decode("ascii", "replace")
                   if ln else None)
    return out


_SECTION_PARSERS = {TRACE_MAGIC: _parse_trace_section,
                    DEADLINE_MAGIC: _parse_deadline_section,
                    TENANT_MAGIC: _parse_tenant_section}


def _walk_sections(r: "_Reader", n_req: int) -> Dict[bytes, list]:
    """Parse the optional trailer sections (any order) to exactly EOF.
    An unknown magic is a malformed payload, not a skippable blob —
    sections carry no length prefix, so skipping is impossible."""
    sections: Dict[bytes, list] = {}
    while r.ofs < len(r.data):
        magic = r.take(4)
        parser = _SECTION_PARSERS.get(magic)
        if parser is None:
            raise CodecError(
                f"unknown trailer section magic {magic!r}")
        if magic in sections:
            raise CodecError(
                f"duplicate trailer section {magic!r}")
        sections[magic] = parser(r, n_req)
    return sections


def _has_section(data: bytes, magic: bytes) -> bool:
    """Whether an intact payload already carries a ``magic`` trailer
    section (malformed trailers report False — the caller's append
    will fail loudly at decode, never silently double-stamp)."""
    idx = data.rfind(magic)
    if idx < 8:          # before any possible batch body
        return False
    try:
        n_req = peek_batch_size(data)
        r = _Reader(data)
        r.ofs = idx
        return magic in _walk_sections(r, n_req)
    except (CodecError, struct.error):
        return False


def attach_trace_trailer(
        data: bytes,
        traceparents: Sequence[Optional[str]]) -> bytes:
    """Append per-request ``traceparent`` headers to an ALREADY
    ENCODED batch (the router's pass-through path never decodes the
    arrays). A payload that already carries a trace trailer is
    returned unchanged — a client that stamped its own trace
    identities wins over the router's."""
    n = peek_batch_size(data)
    if len(traceparents) != n:
        raise CodecError(
            f"trace trailer carries {len(traceparents)} entries for "
            f"a batch of {n} requests")
    if _has_section(data, TRACE_MAGIC):
        return data
    parts: List[bytes] = [data, TRACE_MAGIC, struct.pack("<I", n)]
    for tp in traceparents:
        b = (tp or "").encode("ascii", "replace")
        parts.append(struct.pack("<H", len(b)))
        parts.append(b)
    return b"".join(parts)


def attach_deadline_trailer(
        data: bytes,
        deadlines_ms: Sequence[Optional[float]]) -> bytes:
    """Append per-request REMAINING deadline budgets (ms) to an
    already-encoded batch. ``None`` = unbounded (NaN on the wire).
    Like the trace trailer, a payload that already carries one is
    returned unchanged — the upstream stamp (an external client that
    budgeted its own hops) wins over the router's."""
    n = peek_batch_size(data)
    if len(deadlines_ms) != n:
        raise CodecError(
            f"deadline trailer carries {len(deadlines_ms)} entries "
            f"for a batch of {n} requests")
    if _has_section(data, DEADLINE_MAGIC):
        return data
    parts: List[bytes] = [data, DEADLINE_MAGIC, struct.pack("<I", n)]
    for ms in deadlines_ms:
        parts.append(struct.pack(
            "<d", float("nan") if ms is None else float(ms)))
    return b"".join(parts)


def attach_tenant_trailer(
        data: bytes,
        tenants: Sequence[Optional[str]]) -> bytes:
    """Append per-request tenant ids to an already-encoded batch.
    ``None`` = untagged (0-length on the wire; the consumer maps it to
    the ``default`` tenant). A payload already carrying a tenant
    trailer is returned unchanged — a client that tagged its own
    tenancy wins over the router's header-derived stamp."""
    n = peek_batch_size(data)
    if len(tenants) != n:
        raise CodecError(
            f"tenant trailer carries {len(tenants)} entries for "
            f"a batch of {n} requests")
    if _has_section(data, TENANT_MAGIC):
        return data
    parts: List[bytes] = [data, TENANT_MAGIC, struct.pack("<I", n)]
    for t in tenants:
        b = (t or "").encode("ascii", "replace")
        parts.append(struct.pack("<H", len(b)))
        parts.append(b)
    return b"".join(parts)


def decode_batch_trailers_ex(data: bytes) -> tuple:
    """``(feeds_list, traceparents, deadlines_ms, tenants)`` — the
    full worker-side decode. Each trailer slot is None when the
    payload carries no such section, else one ``Optional`` entry per
    request."""
    r = _Reader(data)
    if r.take(4) != BATCH_MAGIC:
        raise CodecError("not a fleet batch payload")
    feeds = [[r.array() for _ in range(r.u32())]
             for _ in range(r.u32())]
    sections = _walk_sections(r, len(feeds))
    return (feeds, sections.get(TRACE_MAGIC),
            sections.get(DEADLINE_MAGIC),
            sections.get(TENANT_MAGIC))


def decode_batch_trailers(data: bytes) -> tuple:
    """``(feeds_list, traceparents, deadlines_ms)`` — the pre-tenant
    decode shape, kept for callers that do not consume tenancy (the
    ``decode_batch_ex`` back-compat pattern, one generation later)."""
    feeds, traceparents, deadlines, _ = decode_batch_trailers_ex(data)
    return feeds, traceparents, deadlines


def decode_batch_ex(
        data: bytes
) -> tuple:
    """``(feeds_list, traceparents)`` — the pre-deadline decode shape,
    kept for callers that do not consume budgets."""
    feeds, traceparents, _ = decode_batch_trailers(data)
    return feeds, traceparents


def encode_results(
        results: Sequence[Union[Sequence[np.ndarray], BaseException]]
) -> bytes:
    """Encode per-request outcomes: each entry is either the request's
    output-array list or the exception that failed it (only that
    request — a replica-side fault barrier maps per request)."""
    parts: List[bytes] = [RESULTS_MAGIC,
                          struct.pack("<I", len(results))]
    for res in results:
        if isinstance(res, BaseException):
            code = _CODE_OF.get(type(res), _ERR_GENERIC)
            msg = f"{type(res).__name__}: {res}".encode(
                "utf-8", "replace")
            parts.append(struct.pack("<BI", code, len(msg)))
            parts.append(msg)
        else:
            parts.append(struct.pack("<BI", _OK, len(res)))
            for a in res:
                _put_array(parts, np.asarray(a))
    return b"".join(parts)


def decode_results(
        data: bytes
) -> List[Union[List[np.ndarray], BaseException]]:
    r = _Reader(data)
    if r.take(4) != RESULTS_MAGIC:
        raise CodecError("not a fleet results payload")
    out: List[Union[List[np.ndarray], BaseException]] = []
    for _ in range(r.u32()):
        status = r.u8()
        n = r.u32()
        if status == _OK:
            out.append([r.array() for _ in range(n)])
        else:
            msg = r.take(n).decode("utf-8", "replace")
            if status == _ERR_QUOTA:
                # re-typed with its tenant: admit() phrases the
                # message as "tenant '<name>' exceeded ...", so the
                # per-tenant identity survives the wire without a
                # second framing field
                exc = QuotaExceededError(msg)
                m = re.search(r"tenant '([^']+)'", msg)
                if m:
                    exc.tenant = m.group(1)
                out.append(exc)
            else:
                out.append(_EXC_OF.get(status, RuntimeError)(msg))
    return out
