"""Replica supervisor: spawn, watch, respawn, and scale the fleet.

``ReplicaSupervisor`` owns N replica "processes" produced by a
factory. The default ``ProcessReplicaFactory`` spawns real worker
processes (``python -m paddle_tpu.serving.fleet.worker``) that
announce their ephemeral port through an atomically-written file;
``worker.ThreadReplicaFactory`` swaps in in-process replicas for
tests and single-process deployments — the supervisor logic is
identical.

The monitor thread polls each replica: an exit while the fleet is
running is a crash — the replica is respawned after a backoff that
doubles per consecutive crash (``FLAGS_fleet_restart_backoff_ms``),
and ``paddle_fleet_replica_restarts_total`` counts it. The respawned
replica warms from the shared ``FLAGS_compile_cache_dir`` + warmup
manifest, so recovery is a warm scale-out, not a cold start. A
``scale_to(n)`` grows the fleet with the same warm path (the router
picks new replicas up from ``endpoints()``) or retires the
highest-numbered replicas gracefully.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from .worker import read_announce_file

__all__ = ["ReplicaSupervisor", "ProcessReplicaFactory",
           "SubprocessReplica"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        v = flag_value(name)
    except KeyError:
        return default
    return v


class SubprocessReplica:
    """ReplicaProcess protocol over a worker subprocess + its
    announce file."""

    def __init__(self, proc: subprocess.Popen, announce_path: str):
        self.proc = proc
        self.announce_path = announce_path
        self.pid = proc.pid
        self._url: Optional[str] = None

    def url(self) -> Optional[str]:
        if self._url is None:
            info = read_announce_file(self.announce_path)
            if info and info.get("pid") == self.pid:
                self._url = info["url"]
        return self._url

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self):
        if self.proc.poll() is None:
            self.proc.terminate()

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None


class ProcessReplicaFactory:
    """Builds worker subprocesses. ``extra_args`` go to the worker
    CLI verbatim (e.g. ``["--stub", "--stub-device-ms", "8"]`` or
    ``["--model-prefix", "/models/m_v3"]``); ``env`` overlays the
    parent environment — the usual overlay is
    ``FLAGS_compile_cache_dir`` + ``JAX_PLATFORMS``, making every
    spawn a warm start."""

    def __init__(self, *, extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 python: Optional[str] = None,
                 announce_dir: Optional[str] = None,
                 stdout=None, stderr=None):
        self.extra_args = list(extra_args or [])
        self.env = dict(env or {})
        self.host = host
        self.python = python or sys.executable
        self.announce_dir = announce_dir or tempfile.mkdtemp(
            prefix="paddle-fleet-")
        self.stdout = stdout
        self.stderr = stderr
        self._spawn_seq = 0

    def __call__(self, replica_id: int) -> SubprocessReplica:
        self._spawn_seq += 1
        announce = os.path.join(
            self.announce_dir,
            f"replica-{replica_id}.{self._spawn_seq}.json")
        cmd = [self.python, "-m", "paddle_tpu.serving.fleet.worker",
               "--host", self.host, "--port", "0",
               "--announce", announce,
               "--name", f"replica-{replica_id}"] + self.extra_args
        env = dict(os.environ)
        env.update(self.env)
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=self.stdout if self.stdout is not None
            else subprocess.DEVNULL,
            stderr=self.stderr if self.stderr is not None
            else subprocess.DEVNULL)
        return SubprocessReplica(proc, announce)


class _PendingProc:
    """Placeholder proc for a slot whose factory call is in flight.

    Spawning (``subprocess.Popen``, model warmup) happens OUTSIDE the
    supervisor lock — a slow factory must never block ``endpoints()``
    or the monitor — so the slot is published first with this
    sentinel: alive (``poll() is None``, so the monitor never
    "respawns" it) but unannounced (``url() is None``, so the router
    never routes to it). The real proc replaces it under the lock
    once the spawn returns."""

    def poll(self):
        return None

    def url(self):
        return None

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 0


class _Managed:
    """Supervisor-side record of one replica slot."""

    __slots__ = ("replica_id", "proc", "restarts", "respawn_at",
                 "retiring")

    def __init__(self, replica_id: int, proc):
        self.replica_id = replica_id
        self.proc = proc
        self.restarts = 0
        self.respawn_at: Optional[float] = None
        self.retiring = False


class ReplicaSupervisor:
    """Spawns and keeps alive ``n_replicas`` replicas built by
    ``factory(replica_id)``. ``endpoints()`` is the router's
    discovery surface: the currently-announced ``{id: url}`` map
    (a crashed or not-yet-announced replica is absent)."""

    def __init__(self, factory: Callable[[int], object],
                 n_replicas: Optional[int] = None, *,
                 auto_restart: bool = True,
                 restart_backoff_ms: Optional[float] = None,
                 poll_interval_s: float = 0.05,
                 metrics=None, name: str = "fleet"):
        self.factory = factory
        self.n_replicas = int(
            n_replicas if n_replicas is not None
            else _flag("FLAGS_fleet_replicas", 2))
        self.auto_restart = bool(auto_restart)
        self.restart_backoff_ms = float(
            restart_backoff_ms if restart_backoff_ms is not None
            else _flag("FLAGS_fleet_restart_backoff_ms", 200.0))
        self.poll_interval_s = float(poll_interval_s)
        self.name = name
        self._metrics = metrics
        self._lock = threading.Lock()
        self._managed: Dict[int, _Managed] = {}
        self._next_id = 0
        self._stopping = False
        self._monitor: Optional[threading.Thread] = None

    # ------------------------------------------------------ lifecycle
    def _spawn_into(self, rid: int):
        """Run the factory for an already-reserved slot — called with
        the lock NOT held — then publish the proc under the lock. If
        the slot was retired or the supervisor stopped while the
        spawn was in flight, the fresh proc is terminated instead."""
        try:
            proc = self.factory(rid)
        except Exception:
            with self._lock:
                self._managed.pop(rid, None)
            raise
        with self._lock:
            m = self._managed.get(rid)
            orphaned = self._stopping or m is None or m.retiring
            if not orphaned:
                m.proc = proc
        if orphaned:
            proc.terminate()

    def start(self) -> "ReplicaSupervisor":
        with self._lock:
            if self._stopping:
                raise RuntimeError("supervisor already stopped")
            new_ids = []
            while self._next_id < self.n_replicas:
                rid = self._next_id
                self._next_id += 1
                self._managed[rid] = _Managed(rid, _PendingProc())
                new_ids.append(rid)
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop,
                    name=f"fleet-supervisor-{self.name}", daemon=True)
                self._monitor.start()
        for rid in new_ids:
            self._spawn_into(rid)
        return self

    def stop(self, timeout: float = 10.0):
        with self._lock:
            self._stopping = True
            managed = list(self._managed.values())
        for m in managed:
            m.proc.terminate()
        deadline = time.monotonic() + timeout
        for m in managed:
            left = max(0.0, deadline - time.monotonic())
            if m.proc.wait(left) is None:
                m.proc.kill()
        t = self._monitor
        if t is not None and t.is_alive() and \
                t is not threading.current_thread():
            t.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------ scaling
    def scale_to(self, n: int):
        """Grow (spawn warm replicas) or shrink (retire the
        highest-numbered ones gracefully) to ``n``."""
        n = int(n)
        to_stop = []
        new_ids = []
        with self._lock:
            self.n_replicas = n
            live = sorted(rid for rid, m in self._managed.items()
                          if not m.retiring)
            for rid in live[n:]:
                m = self._managed[rid]
                m.retiring = True
                to_stop.append(m)
            count = len(live[:n])
            while count < n:
                rid = self._next_id
                self._next_id += 1
                self._managed[rid] = _Managed(rid, _PendingProc())
                new_ids.append(rid)
                count += 1
        for m in to_stop:
            m.proc.terminate()
        for rid in new_ids:
            self._spawn_into(rid)

    # ------------------------------------------------------ discovery
    def endpoints(self) -> Dict[int, str]:
        with self._lock:
            managed = list(self._managed.values())
        out = {}
        for m in managed:
            if m.retiring or m.proc.poll() is not None:
                continue
            url = m.proc.url()
            if url:
                out[m.replica_id] = url
        return out

    def restart_counts(self) -> Dict[int, int]:
        with self._lock:
            return {rid: m.restarts
                    for rid, m in self._managed.items()}

    @property
    def replica_ids(self) -> List[int]:
        with self._lock:
            return sorted(rid for rid, m in self._managed.items()
                          if not m.retiring)

    # ------------------------------------------------------ monitor
    def _monitor_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
                managed = list(self._managed.items())
            now = time.monotonic()
            for rid, m in managed:
                rc = m.proc.poll()
                if rc is None:
                    continue
                if m.retiring:
                    with self._lock:
                        self._managed.pop(rid, None)
                    continue
                if not self.auto_restart:
                    continue
                if m.respawn_at is None:
                    # crash observed: schedule the respawn after a
                    # backoff that doubles per consecutive crash
                    backoff = self.restart_backoff_ms * min(
                        30.0, 2.0 ** min(m.restarts, 5))
                    with self._lock:
                        m.respawn_at = now + backoff / 1e3
                    continue
                if now < m.respawn_at:
                    continue
                try:
                    proc = self.factory(rid)
                except Exception:  # noqa: BLE001 - a failed spawn
                    # retries next tick with the same backoff ladder
                    with self._lock:
                        m.respawn_at = now + \
                            self.restart_backoff_ms / 1e3
                    continue
                with self._lock:
                    if self._stopping:
                        proc.terminate()
                        return
                    m.proc = proc
                    m.restarts += 1
                    m.respawn_at = None
                if self._metrics is not None:
                    self._metrics.count_restart()
            time.sleep(self.poll_interval_s)
