"""Serving observability: per-server metrics + a named registry.

Wired into the rest of the stack rather than freestanding:

- every counter bump mirrors into ``framework.monitor`` (the reference's
  STAT_ADD int64 registry, platform/monitor.cc) under a
  ``serving_<server>_*`` name, so existing monitor consumers see serving
  traffic alongside the framework's other stats;
- batch executions are wrapped in ``profiler.RecordEvent`` spans by the
  server, so the host tracer's chrome export shows serving batches on
  the timeline.

Schema (``snapshot()`` / ``to_json()``)::

    {"server": str,
     "counters": {"submitted", "completed", "rejected", "timed_out",
                  "cancelled", "failed", "batches"},
     "queue": {"depth", "capacity", "peak_depth"},
     "batch_size_hist": {"<rows>": count, ...},
     "padding": {"real_elements", "padded_elements", "waste_ratio"},
     "latency_ms": {"count", "p50", "p95", "p99", "max"},
     "stage_ms": {"count",
                  "assembly" | "dispatch" | "device_wait" | "fetch" |
                  "host" | "device": {"p50", "p95", "p99", "max"},
                  "host_fraction"},
     "compile_cache": {"hits", "misses", "signatures"}}

``stage_ms`` is the per-batch host/device time split from the
pipelined executor: ``assembly`` (staging-pool copy), ``dispatch``
(device_put + async dispatch), ``device_wait`` (blocking until device
compute finishes), ``fetch`` (device->host transfer). ``host`` =
assembly+dispatch+fetch, ``device`` = device_wait, and
``host_fraction`` is sum(host)/sum(host+device) over the window — the
continuously measured version of PERF.md's "~95% host overhead" claim.
"""
from __future__ import annotations

import json
import math
import threading
from collections import deque
from typing import Dict, List, Optional

__all__ = ["ServingMetrics", "register", "get", "unregister",
           "all_snapshots"]

_COUNTERS = ("submitted", "completed", "rejected", "timed_out",
             "cancelled", "failed", "batches")


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return float(sorted_vals[k])


class ServingMetrics:
    """Thread-safe metric sink for one server. Latency keeps a bounded
    window (``window`` most recent request latencies) so a long-running
    server's percentiles track current behavior, not its whole life."""

    def __init__(self, name: str = "default", window: int = 2048):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {c: 0 for c in _COUNTERS}
        self._batch_hist: Dict[int, int] = {}
        self._latency = deque(maxlen=int(window))
        self._queue_depth = 0
        self._queue_capacity = 0
        self._peak_depth = 0
        self._real_elements = 0
        self._padded_elements = 0
        self._compile_hits = 0
        self._compile_misses = 0
        self._signatures = set()
        self._stages = {k: deque(maxlen=int(window))
                        for k in ("assembly", "dispatch", "device_wait",
                                  "fetch", "host", "device")}

    # ---- recording ----
    def count(self, name: str, n: int = 1):
        from ..framework import monitor
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        monitor.stat_add(f"serving_{self.name}_{name}", n)

    def queue_depth(self, depth: int, capacity: int):
        with self._lock:
            self._queue_depth = depth
            self._queue_capacity = capacity
            self._peak_depth = max(self._peak_depth, depth)

    def observe_batch(self, rows: int, real_elements: int,
                      padded_elements: int):
        from ..framework import monitor
        with self._lock:
            self._counters["batches"] += 1
            self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1
            self._real_elements += real_elements
            self._padded_elements += padded_elements
        monitor.stat_add(f"serving_{self.name}_batches", 1)

    def observe_latency(self, ms: float):
        with self._lock:
            self._latency.append(float(ms))

    def observe_latency_many(self, ms_list):
        """Bulk latency append: one lock acquisition per batch instead
        of one per request (the completion stage resolves whole batches
        at a time)."""
        with self._lock:
            self._latency.extend(float(m) for m in ms_list)

    def observe_stage_times(self, assembly_ms: float, dispatch_ms: float,
                            device_wait_ms: float, fetch_ms: float):
        """Per-batch pipeline stage durations; host = everything the
        host CPU did (assembly + dispatch + fetch), device = time spent
        waiting on device compute."""
        with self._lock:
            self._stages["assembly"].append(float(assembly_ms))
            self._stages["dispatch"].append(float(dispatch_ms))
            self._stages["device_wait"].append(float(device_wait_ms))
            self._stages["fetch"].append(float(fetch_ms))
            self._stages["host"].append(
                float(assembly_ms + dispatch_ms + fetch_ms))
            self._stages["device"].append(float(device_wait_ms))

    def observe_compile(self, hit: bool, signature=None):
        with self._lock:
            if hit:
                self._compile_hits += 1
            else:
                self._compile_misses += 1
                if signature is not None:
                    self._signatures.add(signature)

    # ---- export ----
    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            padded = self._padded_elements
            real = self._real_elements
            return {
                "server": self.name,
                "counters": dict(self._counters),
                "queue": {"depth": self._queue_depth,
                          "capacity": self._queue_capacity,
                          "peak_depth": self._peak_depth},
                "batch_size_hist": {str(k): v for k, v in
                                    sorted(self._batch_hist.items())},
                "padding": {
                    "real_elements": real,
                    "padded_elements": padded,
                    "waste_ratio": (padded - real) / padded if padded
                    else 0.0},
                "latency_ms": {
                    "count": len(lat),
                    "p50": _percentile(lat, 50),
                    "p95": _percentile(lat, 95),
                    "p99": _percentile(lat, 99),
                    "max": lat[-1] if lat else 0.0},
                "stage_ms": self._stage_snapshot(),
                "compile_cache": {"hits": self._compile_hits,
                                  "misses": self._compile_misses,
                                  "signatures": len(self._signatures)},
            }

    def _stage_snapshot(self) -> dict:
        """Per-stage percentiles + host fraction (lock held)."""
        out = {"count": len(self._stages["host"])}
        for name, window in self._stages.items():
            vals = sorted(window)
            out[name] = {"p50": _percentile(vals, 50),
                         "p95": _percentile(vals, 95),
                         "p99": _percentile(vals, 99),
                         "max": vals[-1] if vals else 0.0}
        host = sum(self._stages["host"])
        device = sum(self._stages["device"])
        out["host_fraction"] = host / (host + device) \
            if host + device else 0.0
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def export_json(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json(indent=1))


# ---- named registry (one entry per live server) ----
_reg_lock = threading.Lock()
_registry: Dict[str, ServingMetrics] = {}


def register(m: ServingMetrics) -> ServingMetrics:
    with _reg_lock:
        _registry[m.name] = m
    return m


def get(name: str) -> Optional[ServingMetrics]:
    with _reg_lock:
        return _registry.get(name)


def unregister(name: str):
    with _reg_lock:
        _registry.pop(name, None)


def all_snapshots() -> dict:
    with _reg_lock:
        servers = list(_registry.values())
    return {m.name: m.snapshot() for m in servers}
