"""Serving observability: per-server metrics + a named registry.

Backed by the unified telemetry layer (``paddle_tpu.observability``)
rather than freestanding counters: every recording lands in typed
metric families on the process-wide registry, so a scraped ``/metrics``
page (see ``FLAGS_serving_telemetry_port``) shows serving traffic in
Prometheus text format —

    paddle_serving_requests_total{server="default",event="completed"}
    paddle_serving_latency_ms_bucket{server="default",le="25"}
    paddle_serving_stage_ms_bucket{server="default",stage="host",...}
    paddle_serving_compile_total{server="default",result="miss"}

— while ``snapshot()`` keeps the historical JSON schema byte-for-byte
(below). Counter bumps still mirror into ``framework.monitor`` (itself
a Counter view now) under ``serving_<server>_*`` names, and batch
executions are wrapped in ``profiler.RecordEvent`` spans by the server.

Schema (``snapshot()`` / ``to_json()``)::

    {"server": str,
     "counters": {"submitted", "completed", "rejected", "timed_out",
                  "cancelled", "failed", "batches"},
     "queue": {"depth", "capacity", "peak_depth"},
     "batch_size_hist": {"<rows>": count, ...},
     "padding": {"real_elements", "padded_elements", "waste_ratio"},
     "latency_ms": {"count", "p50", "p95", "p99", "max"},
     "stage_ms": {"count",
                  "assembly" | "dispatch" | "device_wait" | "fetch" |
                  "host" | "device": {"p50", "p95", "p99", "max"},
                  "host_fraction"},
     "compile_cache": {"hits", "misses", "signatures"}}

``stage_ms`` is the per-batch host/device time split from the
pipelined executor: ``assembly`` (staging-pool copy), ``dispatch``
(device_put + async dispatch), ``device_wait`` (blocking until device
compute finishes), ``fetch`` (device->host transfer). ``host`` =
assembly+dispatch+fetch, ``device`` = device_wait, and
``host_fraction`` is sum(host)/sum(host+device) over the window — the
continuously measured version of PERF.md's "~95% host overhead" claim.

Percentiles come from ``observability.PercentileWindow`` (bounded
window of the ``window`` most recent samples, nearest-rank estimator —
the same class the registry's Histogram uses), so a long-running
server's percentiles track current behavior, not its whole life.
"""
from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from ..framework import monitor
from ..observability.registry import (PercentileWindow, _nearest_rank,
                                      default_registry)

__all__ = ["ServingMetrics", "register", "get", "unregister",
           "all_snapshots"]

_COUNTERS = ("submitted", "completed", "rejected", "timed_out",
             "cancelled", "failed", "batches")

_STAGES = ("assembly", "dispatch", "device_wait", "fetch", "host",
           "device")

_ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample (kept for
    callers of the pre-registry module surface; the shared
    implementation lives in observability.registry)."""
    return _nearest_rank(sorted_vals, q)


class ServingMetrics:
    """Thread-safe metric sink for one server, backed by registry
    families. Instantiating a name resets that server's label slice in
    the shared families (a restarted server starts from zero, matching
    the pre-registry behavior)."""

    def __init__(self, name: str = "default", window: int = 2048,
                 registry=None):
        self.name = name
        self._lock = threading.Lock()
        reg = self._registry = registry or default_registry()

        self._f_events = reg.counter(
            "paddle_serving_requests_total",
            "serving request lifecycle events per server",
            ("server", "event"))
        self._f_latency = reg.histogram(
            "paddle_serving_latency_ms",
            "end-to-end request latency (submit -> future resolved)",
            ("server",))
        self._f_stage = reg.histogram(
            "paddle_serving_stage_ms",
            "per-batch pipeline stage durations (host = assembly+"
            "dispatch+fetch, device = device_wait)",
            ("server", "stage"))
        self._f_batch_rows = reg.histogram(
            "paddle_serving_batch_rows",
            "real rows per coalesced device batch", ("server",),
            buckets=_ROW_BUCKETS)
        self._f_queue = reg.gauge(
            "paddle_serving_queue_depth", "current request-queue depth",
            ("server",))
        self._f_capacity = reg.gauge(
            "paddle_serving_queue_capacity", "bounded queue capacity",
            ("server",))
        self._f_peak = reg.gauge(
            "paddle_serving_queue_peak_depth",
            "highest queue depth observed", ("server",))
        self._f_padding = reg.counter(
            "paddle_serving_padding_elements_total",
            "input elements by kind: real (caller-supplied) vs padded "
            "(elements the bucketed device batch actually carries)",
            ("server", "kind"))
        self._f_compile = reg.counter(
            "paddle_serving_compile_total",
            "serving compile-cache lookups by result",
            ("server", "result"))
        self._f_signatures = reg.gauge(
            "paddle_serving_compile_signatures",
            "distinct compiled (signature, padded_rows) entries",
            ("server",))

        # a fresh ServingMetrics owns its server's slice from zero
        for fam in (self._f_events, self._f_latency, self._f_stage,
                    self._f_batch_rows, self._f_queue, self._f_capacity,
                    self._f_peak, self._f_padding, self._f_compile,
                    self._f_signatures):
            fam.clear(server=name)

        self._events = {c: self._f_events.labels(server=name, event=c)
                        for c in _COUNTERS}
        self._h_latency = self._f_latency.labels(server=name)
        self._h_stages = {s: self._f_stage.labels(server=name, stage=s)
                          for s in _STAGES}
        self._h_batch_rows = self._f_batch_rows.labels(server=name)
        self._c_real = self._f_padding.labels(server=name, kind="real")
        self._c_padded = self._f_padding.labels(server=name,
                                                kind="padded")
        self._c_hits = self._f_compile.labels(server=name, result="hit")
        self._c_misses = self._f_compile.labels(server=name,
                                                result="miss")

        # bounded windows for the snapshot percentiles (per instance so
        # each server honors ITS window size; the family windows back
        # the shared /metrics exposition)
        self._latency = PercentileWindow(int(window))
        self._stages = {k: PercentileWindow(int(window))
                        for k in _STAGES}
        self._batch_hist: Dict[int, int] = {}
        self._queue_depth = 0
        self._queue_capacity = 0
        self._peak_depth = 0
        self._real_elements = 0
        self._padded_elements = 0
        self._signatures = set()

    # ---- recording ----
    def _event_child(self, name: str):
        child = self._events.get(name)
        if child is None:
            with self._lock:
                child = self._events.get(name)
                if child is None:
                    child = self._events[name] = self._f_events.labels(
                        server=self.name, event=name)
        return child

    def count(self, name: str, n: int = 1):
        self._event_child(name).inc(n)
        monitor.stat_add(f"serving_{self.name}_{name}", n)

    def queue_depth(self, depth: int, capacity: int):
        with self._lock:
            self._queue_depth = depth
            self._queue_capacity = capacity
            self._peak_depth = max(self._peak_depth, depth)
            peak = self._peak_depth
        self._f_queue.labels(server=self.name).set(depth)
        self._f_capacity.labels(server=self.name).set(capacity)
        self._f_peak.labels(server=self.name).set(peak)

    def observe_batch(self, rows: int, real_elements: int,
                      padded_elements: int):
        with self._lock:
            self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1
            self._real_elements += real_elements
            self._padded_elements += padded_elements
        self._events["batches"].inc()
        self._h_batch_rows.observe(rows)
        self._c_real.inc(real_elements)
        self._c_padded.inc(padded_elements)
        monitor.stat_add(f"serving_{self.name}_batches", 1)

    def observe_latency(self, ms: float):
        with self._lock:
            self._latency.observe(float(ms))
        self._h_latency.observe(ms)

    def observe_latency_many(self, ms_list):
        """Bulk latency append: one lock acquisition per batch instead
        of one per request (the completion stage resolves whole batches
        at a time)."""
        ms_list = [float(m) for m in ms_list]
        with self._lock:
            self._latency.extend(ms_list)
        self._h_latency.observe_many(ms_list)

    def observe_stage_times(self, assembly_ms: float, dispatch_ms: float,
                            device_wait_ms: float, fetch_ms: float):
        """Per-batch pipeline stage durations; host = everything the
        host CPU did (assembly + dispatch + fetch), device = time spent
        waiting on device compute."""
        vals = {"assembly": float(assembly_ms),
                "dispatch": float(dispatch_ms),
                "device_wait": float(device_wait_ms),
                "fetch": float(fetch_ms),
                "host": float(assembly_ms + dispatch_ms + fetch_ms),
                "device": float(device_wait_ms)}
        with self._lock:
            for k, v in vals.items():
                self._stages[k].observe(v)
        for k, v in vals.items():
            self._h_stages[k].observe(v)

    def observe_compile(self, hit: bool, signature=None):
        if hit:
            self._c_hits.inc()
            return
        self._c_misses.inc()
        if signature is not None:
            with self._lock:
                self._signatures.add(signature)
                n = len(self._signatures)
            self._f_signatures.labels(server=self.name).set(n)

    # ---- export ----
    def snapshot(self) -> dict:
        with self._lock:
            counters = {c: 0 for c in _COUNTERS}
            counters.update({ev: int(child.value)
                             for ev, child in self._events.items()})
            padded = self._padded_elements
            real = self._real_elements
            lat = self._latency.snapshot()
            return {
                "server": self.name,
                "counters": counters,
                "queue": {"depth": self._queue_depth,
                          "capacity": self._queue_capacity,
                          "peak_depth": self._peak_depth},
                "batch_size_hist": {str(k): v for k, v in
                                    sorted(self._batch_hist.items())},
                "padding": {
                    "real_elements": real,
                    "padded_elements": padded,
                    "waste_ratio": (padded - real) / padded if padded
                    else 0.0},
                "latency_ms": lat,
                "stage_ms": self._stage_snapshot(),
                "compile_cache": {"hits": int(self._c_hits.value),
                                  "misses": int(self._c_misses.value),
                                  "signatures": len(self._signatures)},
            }

    def _stage_snapshot(self) -> dict:
        """Per-stage percentiles + host fraction (lock held)."""
        out = {"count": len(self._stages["host"])}
        for name, window in self._stages.items():
            snap = window.snapshot()
            snap.pop("count")
            out[name] = snap
        host = self._stages["host"].sum()
        device = self._stages["device"].sum()
        out["host_fraction"] = host / (host + device) \
            if host + device else 0.0
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def export_json(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json(indent=1))


# ---- named registry (one entry per live server) ----
_reg_lock = threading.Lock()
_registry: Dict[str, ServingMetrics] = {}


def register(m: ServingMetrics) -> ServingMetrics:
    with _reg_lock:
        _registry[m.name] = m
    return m


def get(name: str) -> Optional[ServingMetrics]:
    with _reg_lock:
        return _registry.get(name)


def unregister(name: str):
    with _reg_lock:
        _registry.pop(name, None)


def all_snapshots() -> dict:
    with _reg_lock:
        servers = list(_registry.values())
    return {m.name: m.snapshot() for m in servers}
