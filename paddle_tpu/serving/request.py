"""Request/Future plumbing for the serving layer.

Reference analog: the request objects Paddle Serving / the capi_exp host
loop juggle around AnalysisPredictor. Here a request is a list of numpy
feed arrays (ordered by the predictor's feed names) plus a
``concurrent.futures.Future`` the caller blocks on; the dynamic batcher
(batcher.py) owns the queue of these and the server worker resolves the
futures.
"""
from __future__ import annotations

import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["QueueFullError", "QuotaExceededError",
           "DeadlineExceededError", "ServerClosedError", "Request"]


class QueueFullError(RuntimeError):
    """Raised by ``InferenceServer.submit`` when the bounded request
    queue is at capacity — the backpressure signal; callers shed load or
    retry with their own policy instead of growing an unbounded queue."""


class QuotaExceededError(QueueFullError):
    """Per-TENANT shed: the tenant's token-bucket quota (or a
    preemption by a higher priority class) rejected this request while
    other tenants keep flowing. Subclasses ``QueueFullError`` so every
    untyped shed path (HTTP 429 mapping, retry classification, loadgen
    accounting) keeps treating it as load shedding; typed consumers
    read ``.tenant`` for the per-tenant decision."""

    def __init__(self, message: str = "tenant quota exceeded",
                 tenant: str = "default"):
        super().__init__(message)
        self.tenant = tenant


class DeadlineExceededError(TimeoutError):
    """Set on a request's future when its deadline passed before the
    batcher could schedule it (the request is dropped, never run)."""


class ServerClosedError(RuntimeError):
    """Raised by ``submit`` after shutdown began, and set on still-queued
    futures when shutdown is not draining."""


class Request:
    """One inference request: per-feed arrays + the future resolved with
    the per-request output list (outputs unpadded back to the request's
    own rows / sequence lengths).

    ``trace`` (a ``tracing.TraceContext`` or None) marks the request as
    traced: the server emits queue/assembly/dispatch/device_wait/fetch
    spans into its trace. Warmup requests construct Request directly
    and never carry one — warmup is structurally excluded from the
    flight recorder, like it is from traffic metrics."""

    __slots__ = ("feeds", "rows", "future", "submit_t", "deadline",
                 "signature", "orig_seq", "trace", "t_wall_ns",
                 "tenant")

    def __init__(self, feeds: List[np.ndarray], rows: int,
                 signature: Tuple, orig_seq: Optional[List[int]] = None,
                 timeout_ms: Optional[float] = None, trace=None,
                 tenant: Optional[str] = None):
        self.feeds = feeds
        self.rows = rows
        self.signature = signature
        self.orig_seq = orig_seq
        self.tenant = tenant
        self.future: Future = Future()
        self.submit_t = time.monotonic()
        self.deadline = (self.submit_t + timeout_ms / 1e3
                         if timeout_ms else None)
        self.trace = trace
        self.t_wall_ns = time.time_ns() if trace is not None else 0

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline

    def latency_ms(self) -> float:
        return (time.monotonic() - self.submit_t) * 1e3
