"""paddle_tpu.serving — dynamic-batching inference serving.

The request-level layer above ``paddle_tpu.inference``: the reference
ships a full serving stack around its engine (capi_exp / Paddle
Inference, SURVEY §1/§2.4); TPU-native, the engine is the AOT-compiled
XLA program and THIS package is the serving shell around it.

Pieces:

- ``InferenceServer`` (server.py): owns a Predictor; ``submit(feed) ->
  Future`` / bulk ``submit_many`` / synchronous ``serve_forever``;
  graceful ``shutdown(drain=True)``; ``warmup(bucket_specs)``
  pre-compiles the shape lattice. Execution is a 3-stage pipeline
  (staging-pool host assembly -> jitted async dispatch with donated
  inputs -> completion thread), ``FLAGS_serving_pipeline_depth``
  batches in flight, so host assembly overlaps device compute;
  depth 0 restores the synchronous executor.
- ``DynamicBatcher`` (batcher.py): bounded queue with backpressure
  (``QueueFullError``), per-request deadlines
  (``DeadlineExceededError``), max_batch_size/max_wait_ms coalescing;
  any FULL shape bucket dispatches immediately instead of waiting out
  an older bucket's window.
- ``ShapeBucketPolicy`` / ``BucketSpec`` (bucketing.py): power-of-two
  batch + sequence-length buckets with zero padding and
  unpad-on-fetch, keeping the XLA compile cache bounded and warm.
- ``ServingMetrics`` (metrics.py): queue depth, batch-size histogram,
  padding-waste ratio, latency percentiles, compile-cache hit rate,
  per-batch host/device stage split (``stage_ms``) — JSON-exportable,
  mirrored into framework.monitor, stage spans on the host tracer's
  chrome export.
- ``wrap_capi`` (capi.py): the hook pd_capi.cc calls so C clients get
  request batching behind ``FLAGS_serving_capi_batching``.
- ``generation`` (subpackage): autoregressive decode serving —
  continuous batching over a paged KV cache with streaming token
  futures (``GenerationServer.submit_generate``); knobs under
  ``FLAGS_decode_*``.
- ``fleet`` (subpackage): multi-replica serving — a front-end
  ``FleetRouter`` over N supervised replica worker processes with
  readiness-based routing, load shedding, warm scale-out from the
  shared compile cache, and rolling hot weight swap; knobs under
  ``FLAGS_fleet_*``.
- ``scheduling`` (subpackage): multi-tenant admission control
  (per-tenant token buckets, weighted-fair queuing, priority classes,
  typed ``QuotaExceededError`` sheds) and the ``FleetAutoscaler``
  control loop driving ``ReplicaSupervisor.scale_to``; knobs under
  ``FLAGS_sched_*`` / ``FLAGS_autoscale_*``.

Requests are traceable end to end: under ``FLAGS_trace_sample_rate``
(or an ambient ``tracing.use_context``), every pipeline stage emits a
typed span — queue wait, host assembly, device dispatch, device wait,
fetch; prefill and per-iteration decode for generation — into the
``paddle_tpu.observability.tracing`` flight recorder (``/tracez``),
stitched across router/worker processes by trace id.

Knobs: ``FLAGS_serving_*`` in framework/flags.py.
"""
from __future__ import annotations

from . import generation  # noqa: F401  (decode-serving sub-namespace)
from . import metrics  # noqa: F401  (the registry sub-namespace)
from .batcher import DynamicBatcher
from .bucketing import BucketSpec, ShapeBucketPolicy, next_pow2
from .capi import wrap_capi
from .mesh import ServingMesh, serving_mesh_from_flags
from .metrics import ServingMetrics
from .request import (DeadlineExceededError, QueueFullError,
                      QuotaExceededError, Request, ServerClosedError)
from .server import InferenceServer
from . import fleet  # noqa: F401,E402  (after server: fleet wraps it)
from . import scheduling  # noqa: F401,E402  (admission + autoscaling)

__all__ = [
    "InferenceServer", "DynamicBatcher", "ShapeBucketPolicy",
    "BucketSpec", "ServingMetrics", "Request", "QueueFullError",
    "QuotaExceededError", "DeadlineExceededError", "ServerClosedError",
    "wrap_capi", "next_pow2", "metrics", "generation", "fleet",
    "scheduling", "ServingMesh", "serving_mesh_from_flags",
]
