"""FleetAutoscaler: the control loop that finally calls ``scale_to``.

PR 11 built the burn-rate alert sinks "explicitly as the autoscaler
surface"; PR 8's supervisor has had a warm ``scale_to(n)`` since the
fleet landed. This closes the loop:

- **Scale OUT** when the fast-burn page fires (the SLO is burning at
  page severity), the queue backs up past ``queue_high``, or decode
  occupancy saturates — one step of ``step`` replicas, capped at
  ``max_replicas``.
- **Scale IN** only when it is provably quiet: no burn-rate rule
  firing at all (fast OR slow), queue near-empty, occupancy low, and
  the quiet has lasted ``scale_in_quiet_s`` — one replica at a time,
  floored at ``min_replicas``.
- **Hysteresis**: a global ``cooldown_s`` between scale actions in
  either direction, plus the asymmetric quiet requirement above, so an
  oscillating load cannot flap the fleet (tested under a square-wave
  load in tests/test_scheduling.py).

The loop is clock-injected and ``evaluate()`` is a pure step callable
from tests; ``start()`` runs it on a daemon thread every
``interval_s``. Decisions (timestamp, old -> new, reason, signals) are
kept in a bounded log exported on ``/schedz`` and counted on
``paddle_autoscale_*`` metrics.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, Optional

from .metrics import AutoscaleMetrics

__all__ = ["FleetAutoscaler"]


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        return flag_value(name)
    except KeyError:
        return default


class FleetAutoscaler:
    """Drives ``supervisor.scale_to(n)`` from SLO burn-rate alerts +
    queue depth + decode occupancy.

    ``monitor`` is an ``SLOMonitor`` (or None): the autoscaler
    registers an alert sink named ``autoscaler-<name>`` and tracks
    which burn rules are currently firing. ``queue_depth_fn`` /
    ``occupancy_fn`` are pull signals (callables returning a number;
    None disables that signal).
    """

    def __init__(self, supervisor, *, monitor=None,
                 queue_depth_fn: Optional[Callable[[], float]] = None,
                 occupancy_fn: Optional[Callable[[], float]] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 scale_in_quiet_s: Optional[float] = None,
                 queue_high: Optional[float] = None,
                 occupancy_high: Optional[float] = None,
                 step: int = 1, interval_s: Optional[float] = None,
                 now=None, name: str = "fleet", metrics=None,
                 decision_log: int = 256):
        import time as _time
        self.supervisor = supervisor
        self.monitor = monitor
        self.queue_depth_fn = queue_depth_fn
        self.occupancy_fn = occupancy_fn
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _flag("FLAGS_autoscale_min_replicas", 1))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _flag("FLAGS_autoscale_max_replicas", 8))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else _flag("FLAGS_autoscale_cooldown_s", 30.0))
        self.scale_in_quiet_s = float(
            scale_in_quiet_s if scale_in_quiet_s is not None
            else _flag("FLAGS_autoscale_scale_in_quiet_s", 120.0))
        self.queue_high = float(
            queue_high if queue_high is not None
            else _flag("FLAGS_autoscale_queue_high", 16.0))
        self.occupancy_high = float(
            occupancy_high if occupancy_high is not None
            else _flag("FLAGS_autoscale_occupancy_high", 0.85))
        self.step = max(1, int(step))
        self.interval_s = float(
            interval_s if interval_s is not None
            else _flag("FLAGS_autoscale_interval_s", 5.0))
        self.name = name
        self.metrics = metrics if metrics is not None \
            else AutoscaleMetrics(name)
        self._now = now or _time.monotonic
        self._lock = threading.Lock()
        self._firing: Dict[tuple, dict] = {}   # (slo, rule) -> alert
        self._last_action_t: Optional[float] = None
        self._quiet_since: Optional[float] = self._now()
        self._decisions: deque = deque(maxlen=int(decision_log))
        self._evaluations = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sink_name = f"autoscaler-{name}"
        if monitor is not None:
            monitor.add_alert_sink(self._sink_name, self._on_alert)

    # ------------------------------------------------------ signals
    def _on_alert(self, alert: dict):
        """SLOMonitor sink: called on firing-state transitions."""
        key = (alert.get("slo"), alert.get("rule"))
        with self._lock:
            if alert.get("firing"):
                self._firing[key] = dict(alert)
            else:
                self._firing.pop(key, None)

    def _burn_state(self):
        with self._lock:
            fast = any(r == "fast_burn" for _, r in self._firing)
            slow = any(r == "slow_burn" for _, r in self._firing)
        return fast, slow

    def _pull(self, fn) -> float:
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:  # noqa: BLE001 - a dead signal reads 0, the
            return 0.0     # loop must outlive its sensors

    # ------------------------------------------------------ the loop
    def evaluate(self) -> Optional[dict]:
        """One control step: read signals, maybe scale. Returns the
        decision record when a scale action was taken, else None."""
        now = self._now()
        fast, slow = self._burn_state()
        depth = self._pull(self.queue_depth_fn)
        occ = self._pull(self.occupancy_fn)
        current = len(self.supervisor.replica_ids)
        m = self.metrics
        if m is not None:
            m.set_signal("fast_burn", 1.0 if fast else 0.0)
            m.set_signal("slow_burn", 1.0 if slow else 0.0)
            m.set_signal("queue_depth", depth)
            m.set_signal("occupancy", occ)
        busy = fast or slow or depth > self.queue_high / 2.0 \
            or occ > self.occupancy_high / 2.0
        with self._lock:
            self._evaluations += 1
            if busy:
                self._quiet_since = None
            elif self._quiet_since is None:
                self._quiet_since = now
            quiet_since = self._quiet_since
            last_action = self._last_action_t
        in_cooldown = last_action is not None and \
            now - last_action < self.cooldown_s

        target, reason = current, None
        if fast:
            target, reason = current + self.step, "fast_burn_page"
        elif depth > self.queue_high:
            target, reason = current + self.step, "queue_depth"
        elif occ > self.occupancy_high:
            target, reason = current + self.step, "occupancy"
        elif (not fast and not slow and quiet_since is not None
              and now - quiet_since >= self.scale_in_quiet_s):
            target, reason = current - 1, "slow_burn_quiet"
        target = max(self.min_replicas,
                     min(self.max_replicas, target))
        if target == current or reason is None:
            return None
        if in_cooldown:
            return None
        direction = "out" if target > current else "in"
        self.supervisor.scale_to(target)
        decision = {
            "t": round(now, 3), "from": current, "to": target,
            "direction": direction, "reason": reason,
            "signals": {"fast_burn": fast, "slow_burn": slow,
                        "queue_depth": round(depth, 2),
                        "occupancy": round(occ, 3)},
        }
        with self._lock:
            self._last_action_t = now
            if direction == "in":
                # a scale-in resets the quiet clock: the smaller fleet
                # must prove itself quiet again before shrinking more
                self._quiet_since = now
            self._decisions.append(decision)
        if m is not None:
            m.count_decision(direction, reason)
            m.set_target(target)
        return decision

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - the control loop must
                pass           # survive a transient supervisor error

    def start(self) -> "FleetAutoscaler":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop,
                    name=f"autoscaler-{self.name}", daemon=True)
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(2.0)
        if self.monitor is not None:
            try:
                self.monitor.remove_alert_sink(self._sink_name)
            except Exception:  # noqa: BLE001 - sink may be gone
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------ export
    def snapshot(self) -> dict:
        with self._lock:
            firing = [{"slo": s, "rule": r}
                      for (s, r) in sorted(self._firing)]
            decisions = list(self._decisions)[-32:]
            evaluations = self._evaluations
            last_action = self._last_action_t
        return {
            "name": self.name,
            "replicas": len(self.supervisor.replica_ids),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "cooldown_s": self.cooldown_s,
            "scale_in_quiet_s": self.scale_in_quiet_s,
            "queue_high": self.queue_high,
            "occupancy_high": self.occupancy_high,
            "evaluations": evaluations,
            "last_action_t": last_action,
            "firing": firing,
            "decisions": decisions,
        }
